"""Roofline accounting for the sort-based kernel designs (VERDICT round-2
item 2): how close does each op run to the HBM-bandwidth bound implied by
its algorithm?

Model
-----
TPU XLA ``sort`` is a bitonic sorting network: ``P(n) = k*(k+1)/2`` passes
for ``k = ceil(log2 n)``, each pass streaming every operand lane once.
The width-adaptive radix engine (ops/radix.py) replaces that with
``ceil(d/r)`` stable histogram passes for a d-bit key stack; each traced
``radix_pass`` pjit is priced as one streamed pass of its operands and
folded into the same ``sort_pass_bytes`` bucket, so the radix/bitonic
ratio of modeled sort bytes is directly the engine's win.
Gathers/scatters pay PER ELEMENT (~4-9 ns each on v5e at the narrow row
widths the packed codec uses — measured round 3 via the join stage
profile), modeled as ``GATHER_PASS_EQ`` sequential-pass equivalents per
operand byte. Everything elementwise fuses into one read + one write pass
(XLA fusion).

The op's **model time** is total modeled traffic / peak HBM bandwidth; the
**%membw** column of BENCH_TPU.md is ``model_time / measured_time`` — the
fraction of the algorithm's own bandwidth bound the implementation
achieves. A low %membw means dispatch overhead or unfused overhead; a high
%membw with a slow op means the *algorithm* is the cost (too many passes)
— that is the signal a Pallas kernel with fewer passes can cash in.

The traffic count is not hand-maintained: ``analyze(fn, *args)`` traces the
jitted function and walks the ClosedJaxpr, summing operand bytes per sort
(weighted by its pass count), per gather/scatter (weighted by
GATHER_PASS_EQ), and one pass over everything else that touches data.

Usage:
    from benchmarks.roofline import analyze, model_seconds
    rep = analyze(fn, *example_args)
    t_model = model_seconds(rep, hbm_gbps=819)
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict

import jax
import numpy as np

# v5e (tpu v5 litepod) peak HBM bandwidth, GB/s. Override per device.
HBM_GBPS_DEFAULT = 819.0
# Re-calibrated round 3 on the live chip (benchmarks/profile_join_pieces.py
# stage deltas at 16M rows): the join's packed left gather measured 291 ms
# for ~600 MB of in+out operand bytes -> 291ms * 819GB/s / 600MB ~= 400
# pass-equivalents; the repeat scatter gives ~500 by the same arithmetic.
# (Round 2's "~10x a sequential pass" compared against an eager-fence
# "sequential pass" that was mostly dispatch latency — off by ~40x.)
# Per-element engines on this chip cost ~4-9 ns/element regardless of row
# width at narrow rows, so this UNDERSTATES wide-row gathers' efficiency;
# treat gather/scatter-heavy model times as a calibrated cost model, not a
# bandwidth bound — the byte-vs-element gap IS the Pallas-gather prize.
GATHER_PASS_EQ = 400.0

_SORT_PRIMS = {"sort"}
_GATHER_PRIMS = {"gather", "dynamic_slice", "take"}
_SCATTER_PRIMS = {
    "scatter", "scatter-add", "scatter_add", "scatter_max", "scatter_min",
    "scatter_mul",
}


def _nbytes(aval) -> int:
    try:
        return int(np.prod(aval.shape, dtype=np.int64)) * aval.dtype.itemsize
    except Exception:
        return 0


def _bitonic_passes(n: int) -> float:
    if n <= 1:
        return 1.0
    k = math.ceil(math.log2(n))
    return k * (k + 1) / 2.0


@dataclass
class Report:
    sort_bytes_per_pass: int = 0
    sort_pass_bytes: float = 0.0  # sum over sorts: operand bytes * passes
    sort_count: int = 0
    sort_passes: float = 0.0  # total modeled passes across all sorts
    radix_passes: int = 0  # stable histogram passes (ops/radix.py)
    radix_pass_bytes: float = 0.0  # sum over radix passes: streamed bytes
    gather_bytes: float = 0.0  # pass-equivalent weighted
    scatter_bytes: float = 0.0
    elementwise_bytes: float = 0.0
    collective_bytes: int = 0
    collective_count: int = 0
    by_prim: Dict[str, float] = field(default_factory=dict)

    @property
    def total_model_bytes(self) -> float:
        return (
            self.sort_pass_bytes
            + self.gather_bytes
            + self.scatter_bytes
            + self.elementwise_bytes
        )


def _merge_scaled(rep: Report, sub: Report, scale: float) -> None:
    rep.sort_bytes_per_pass += int(sub.sort_bytes_per_pass * scale)
    rep.sort_pass_bytes += sub.sort_pass_bytes * scale
    rep.sort_count += int(sub.sort_count * scale)
    rep.sort_passes += sub.sort_passes * scale
    rep.radix_passes += int(sub.radix_passes * scale)
    rep.radix_pass_bytes += sub.radix_pass_bytes * scale
    rep.gather_bytes += sub.gather_bytes * scale
    rep.scatter_bytes += sub.scatter_bytes * scale
    rep.elementwise_bytes += sub.elementwise_bytes * scale
    rep.collective_bytes += int(sub.collective_bytes * scale)
    rep.collective_count += int(sub.collective_count * scale)
    for k, v in sub.by_prim.items():
        rep.by_prim[k] = rep.by_prim.get(k, 0.0) + v * scale


def _walk(jaxpr, rep: Report) -> None:
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "scan":
            # a scan body executes `length` times: walk it once and scale
            # (the K-sliced fused join runs its K rounds in ONE scan — an
            # unscaled walk under-reports its collectives/sorts by K).
            # `while` has no static trip count and stays counted once.
            sub = eqn.params.get("jaxpr")
            inner = getattr(sub, "jaxpr", sub)
            if inner is not None and hasattr(inner, "eqns"):
                trips = int(eqn.params.get("length", 1))
                sub_rep = Report()
                _walk(inner, sub_rep)
                _merge_scaled(rep, sub_rep, trips)
            continue
        if prim == "pallas_call":
            # a hand-scheduled kernel: price it as STREAMED bytes (one read
            # of inputs + one write of outputs) and do NOT recurse into the
            # kernel body: its jnp.take runs on VMEM-resident vregs, and
            # pricing it at the HBM per-element gather rate
            # (GATHER_PASS_EQ) would overstate traffic ~400x — beating that
            # rate is the kernel's entire purpose. Known bias: the windowed
            # expand actually DMAs ~1.03 * L * n_out bytes of window READS
            # (output-proportional), while this prices reads at L * cap —
            # in heavy-repeat regimes (n_out >> cap) actual read traffic
            # exceeds the model by up to ~2x, so a low measured %membw on
            # expand-heavy ops partly reflects window re-reads, not only
            # dispatch overhead.
            w = sum(
                _nbytes(x.aval) for x in eqn.invars if hasattr(x, "aval")
            ) + sum(
                _nbytes(x.aval) for x in eqn.outvars if hasattr(x, "aval")
            )
            rep.elementwise_bytes += w
            rep.by_prim[prim] = rep.by_prim.get(prim, 0.0) + w
            continue
        if prim == "pjit" and eqn.params.get("name") == "radix_pass":
            # ONE stable histogram pass of the width-adaptive radix sort
            # (ops/radix.py): the pass streams its operands (encoded key
            # lane + permutation) a small constant number of times —
            # histogram, rank, scatter all fuse over the same n rows. The
            # R×n one-hot intermediates live in registers/fused loops, so
            # price streamed in+out bytes and do NOT recurse (recursing
            # would bill the rank gather at GATHER_PASS_EQ and the
            # one-hot at R× the lane bytes — the same overstatement the
            # pallas_call rule avoids). Folding into sort_pass_bytes
            # keeps total_model_bytes comparable across impls: the
            # radix/bitonic ratio of sort_pass_bytes IS the modeled win.
            w = sum(
                _nbytes(x.aval) for x in eqn.invars if hasattr(x, "aval")
            ) + sum(
                _nbytes(x.aval) for x in eqn.outvars if hasattr(x, "aval")
            )
            rep.radix_passes += 1
            rep.radix_pass_bytes += w
            rep.sort_pass_bytes += w
            rep.sort_passes += 1
            rep.sort_count += 1
            rep.by_prim["radix_pass"] = rep.by_prim.get("radix_pass", 0.0) + w
            continue
        # recurse into nested jaxprs (pjit/closed_call/scan/while/cond/
        # shard_map). A param may hold a raw Jaxpr (has .eqns) or a
        # ClosedJaxpr (has .jaxpr) — shard_map uses the former.
        def _sub(v):
            if hasattr(v, "eqns"):
                return v
            inner = getattr(v, "jaxpr", None)
            return inner if inner is not None and hasattr(inner, "eqns") else None

        for v in eqn.params.values():
            sub = _sub(v)
            if sub is not None:
                _walk(sub, rep)
            elif isinstance(v, (list, tuple)):
                for vi in v:
                    sub = _sub(vi)
                    if sub is not None:
                        _walk(sub, rep)
        if prim in (
            "pjit", "closed_call", "custom_jvp_call", "custom_vjp_call",
            "shard_map", "cond", "scan", "while", "remat", "checkpoint",
        ):
            # container primitives: their bodies were just recursed into;
            # adding the container's own in/out bytes would double-count
            # every jit/shard_map boundary. (scan bodies are scaled by trip
            # count above; `while` bodies are still counted once.)
            continue
        in_bytes = sum(_nbytes(x.aval) for x in eqn.invars if hasattr(x, "aval"))
        out_bytes = sum(_nbytes(x.aval) for x in eqn.outvars if hasattr(x, "aval"))
        if prim in _SORT_PRIMS:
            n = 0
            for x in eqn.invars:
                if hasattr(x, "aval") and x.aval.shape:
                    n = max(n, int(x.aval.shape[eqn.params.get("dimension", -1)]))
            passes = _bitonic_passes(n)
            rep.sort_count += 1
            rep.sort_bytes_per_pass += in_bytes
            rep.sort_pass_bytes += in_bytes * passes
            rep.sort_passes += passes
            rep.by_prim["sort"] = rep.by_prim.get("sort", 0.0) + in_bytes * passes
        elif prim in _GATHER_PRIMS:
            w = (in_bytes + out_bytes) * GATHER_PASS_EQ
            rep.gather_bytes += w
            rep.by_prim[prim] = rep.by_prim.get(prim, 0.0) + w
        elif prim in _SCATTER_PRIMS:
            w = (in_bytes + out_bytes) * GATHER_PASS_EQ
            rep.scatter_bytes += w
            rep.by_prim[prim] = rep.by_prim.get(prim, 0.0) + w
        elif prim in ("all_to_all", "all_gather", "psum", "ppermute",
                      "reduce_scatter"):
            rep.collective_bytes += in_bytes
            rep.collective_count += 1
            rep.by_prim[prim] = rep.by_prim.get(prim, 0.0) + in_bytes
        else:
            # elementwise/reduction: fused — count one read + one write
            w = in_bytes + out_bytes
            rep.elementwise_bytes += w


def analyze(fn, *args, **kwargs) -> Report:
    """Trace ``fn(*args)`` and return its modeled HBM traffic."""
    closed = jax.make_jaxpr(fn, **kwargs)(*args)
    rep = Report()
    _walk(closed.jaxpr, rep)
    return rep


def traced_collectives(op, warm: bool = True):
    """Run ``op`` with engine kernel recording on and return
    (total traced collective count, per-program collective bytes for the
    programs that issue any). The shared accounting of the shuffle bench's
    CI gate and tests/test_shuffle_chunked.py. ``warm=True`` runs ``op``
    once first so compilation happens outside the recorded call."""
    from cylon_tpu import engine

    if warm:
        op()
    engine.record_kernels(True)
    try:
        op()
    finally:
        kernels = engine.recorded_kernels()
        engine.record_kernels(False)
    count, per_bytes = 0, []
    for fn, args in kernels:
        rep = analyze(fn, *args)
        count += rep.collective_count
        if rep.collective_count:
            per_bytes.append(rep.collective_bytes)
    return count, per_bytes


def model_seconds(rep: Report, hbm_gbps: float = HBM_GBPS_DEFAULT) -> float:
    """Bandwidth-bound lower time for the modeled traffic."""
    return rep.total_model_bytes / (hbm_gbps * 1e9)


def pct_membw(rep: Report, measured_s: float,
              hbm_gbps: float = HBM_GBPS_DEFAULT) -> float:
    """Fraction (0-1) of the algorithm's bandwidth bound achieved."""
    if measured_s <= 0:
        return 0.0
    return model_seconds(rep, hbm_gbps) / measured_s
