"""Probe: compiled Pallas windowed emit under ``jit(shard_map)`` (VERDICT
r4 item 3).

Round 3 found compiled ``pallas_call`` recursing at trace time when the
emit kernel ran under ``jit(shard_map(...))`` on TPU, and gated the
windowed emit off for multi-chip meshes — exactly where the north star
lives. The suspected trigger was the NESTED jit (`expand_rows` carried its
own @jax.jit inside the shard_map-wrapped kernel); the emit path now calls
the unjitted ``expand_rows_raw``.

Only one real chip is reachable, so this probe runs the production join
kernel on a 1-device mesh with ``CYLON_TPU_FORCE_SHARD_MAP=1`` — the same
``jit(shard_map(kernel-embedding-pallas_call))`` program structure a
multi-chip mesh builds, minus the collectives (which contain no pallas and
are exercised by ``dryrun_multichip``'s 8/16/32-device CPU runs). PASS
here plus the multi-device interpret dryrun is the strongest multi-chip
evidence this environment can produce.

For each expand variant: correctness vs the XLA-gather emit (row-set
equality on a seeded join) and warm timing. One JSON line per variant plus
a summary line; RecursionError is caught and reported as the historical
failure mode.

Usage: python benchmarks/shardmap_pallas_probe.py [--rows N] [--cpu]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("CYLON_TPU_NO_X64", "1")

import numpy as np


def emit_line(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=2_000_000)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import __graft_entry__ as ge

    use_cpu = args.cpu
    if not use_cpu:
        import bench as _b

        use_cpu = not _b.probe_tpu(
            float(os.environ.get("BENCH_INIT_TIMEOUT", 120)),
            int(os.environ.get("BENCH_INIT_TRIES", 2)),
        )
    if use_cpu:
        ge._force_cpu_mesh(1)
        args.rows = min(args.rows, 200_000)

    import jax

    import cylon_tpu as ct

    platform = jax.devices()[0].platform
    n = args.rows
    rng = np.random.default_rng(3)
    ctx = ct.CylonContext.init_distributed(
        ct.TPUConfig(devices=jax.devices()[:1])
    )
    left = ct.Table.from_pydict(
        ctx,
        {
            "k": rng.integers(0, n, n).astype(np.int32),
            "v": rng.normal(size=n).astype(np.float32),
        },
    )
    right = ct.Table.from_pydict(
        ctx,
        {
            "k": rng.integers(0, n, n).astype(np.int32),
            "w": rng.normal(size=n).astype(np.float32),
        },
    )

    import bench as _b
    import pandas as pd

    def run_join():
        out = left.distributed_join(right, on="k", how="inner")
        # fence: one dispatch + one fetch (timing only — its sum covers
        # dead PADDING rows too, whose garbage legitimately differs
        # between emit impls)
        return out, _b.fence(out)

    def canon(tbl):
        df = tbl.to_pandas()
        cols = sorted(df.columns)
        return df[cols].sort_values(cols, kind="mergesort").reset_index(
            drop=True
        )

    # reference result: default gather emit, no shard_map forcing
    base_out, _ = run_join()
    base_rows = base_out.row_count
    base_df = canon(base_out)

    results = []
    for impl in ("take", "take_db", "onehot", "onehot_db"):
        env = {
            "CYLON_TPU_EMIT_IMPL": "windowed",
            "CYLON_TPU_EXPAND_GATHER": impl,
            "CYLON_TPU_FORCE_SHARD_MAP": "1",
        }
        os.environ.update(env)
        row = {
            "benchmark": f"shardmap_pallas_probe_{impl}",
            "platform": platform,
            "rows": n,
            "forced_shard_map": True,
        }
        try:
            t0 = time.perf_counter()
            out1, _ = run_join()
            row["compile_s"] = round(time.perf_counter() - t0, 2)
            # correctness: live-row set equality vs the gather emit (host
            # compare once, outside the timed reps)
            row["ok"] = bool(
                out1.row_count == base_rows and canon(out1).equals(base_df)
            )
            row["rows_out"] = int(out1.row_count)
            best = float("inf")
            for _ in range(args.reps):
                t0 = time.perf_counter()
                _, _sum = run_join()
                best = min(best, time.perf_counter() - t0)
            row["warm_s"] = round(best, 4)
        except RecursionError as e:
            row["ok"] = False
            row["error"] = f"RecursionError: {e}"[:200]
            row["recursion"] = True  # the historical r3 failure mode
        except Exception as e:
            row["ok"] = False
            row["error"] = f"{type(e).__name__}: {e}"[:300]
        finally:
            for k in env:
                os.environ.pop(k, None)
        results.append(row)
        emit_line(row)

    n_ok = sum(r.get("ok") for r in results)
    emit_line(
        {
            "benchmark": "shardmap_pallas_probe_summary",
            "platform": platform,
            "rows": n,
            "variants_ok": n_ok,
            "variants_total": len(results),
            "verdict": "shard_map_pallas_ok" if n_ok == len(results)
            else "shard_map_pallas_blocked",
        }
    )


if __name__ == "__main__":
    main()
