"""One-off stage profile of the speculative join at bench shape.

Times cumulative prefixes of the spec_join pipeline (probe sort, repeat,
left gather, right gather, full) on the live backend so optimization
effort lands on the measured bottleneck, not the modeled one. Each stage
is fenced by a dependent-scalar fetch (tunnel-safe, DCE-proof).
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("CYLON_TPU_NO_X64", "1")

import numpy as np


def main():
    n = int(os.environ.get("BENCH_ROWS", 16_000_000))
    use_cpu = "--cpu" in sys.argv
    if not use_cpu:
        import bench as _b

        use_cpu = not _b.probe_tpu(120, 1)
    if use_cpu:
        import __graft_entry__ as ge

        ge._force_cpu_mesh(1)
        n = min(n, 1_000_000)

    import jax
    import jax.numpy as jnp

    from cylon_tpu.ops import join as _j

    rng = np.random.default_rng(0)
    lk = jnp.asarray(rng.integers(0, n, n).astype(np.int32))
    rk = jnp.asarray(rng.integers(0, n, n).astype(np.int32))
    lv = jnp.asarray(rng.normal(size=n).astype(np.float32))
    rv = jnp.asarray(rng.normal(size=n).astype(np.float32))
    cap = 1 << (n - 1).bit_length()  # bench spec_cap = max(cap_l, cap_r)

    def chk(*arrs):
        s = jnp.float32(0)
        for a in arrs:
            s = s + jnp.sum(a.astype(jnp.float32))
        return s

    def probe_only(a, b):
        lo, cnt, r_order, r_cnt = _j.probe_arrays(
            [(a, None)], [(b, None)], jnp.int32(n), jnp.int32(n), n, n,
            _j.INNER,
        )
        return (lo, cnt, r_order, r_cnt)

    stages = {}
    stages["probe"] = jax.jit(lambda a, b, v, w: chk(*probe_only(a, b)))

    def thru_repeat(a, b):
        lo, cnt, r_order, r_cnt = probe_only(a, b)
        ends = jnp.cumsum(cnt)
        li = _j._repeat_ss(ends, cap)
        return li, lo, cnt, r_order

    stages["probe+repeat"] = jax.jit(
        lambda a, b, v, w: chk(*thru_repeat(a, b))
    )

    def thru_lgather(a, b, v):
        from cylon_tpu.ops.gather import pack_gather

        li, lo, cnt, r_order = thru_repeat(a, b)
        out_l, (base_g, cnt_g) = pack_gather(
            [(a, None), (v, None)], li, extra_lanes=[lo, cnt]
        )
        return out_l, base_g, cnt_g

    def _lg(a, b, v, w):
        out_l, base_g, cnt_g = thru_lgather(a, b, v)
        return chk(*[d for d, _ in out_l], base_g, cnt_g)

    stages["probe+repeat+lgather"] = jax.jit(_lg)

    def full(a, b, v, w):
        out, total, shadow = _j.spec_join(
            [(a, None)], [(b, None)],
            [(a, None), (v, None)], [(b, None), (w, None)],
            jnp.int32(n), jnp.int32(n), _j.INNER, cap,
        )
        return chk(*[d for d, _ in out]) + total.astype(jnp.float32)

    stages["full"] = jax.jit(full)

    # the r4 windowed emit, staged the same way: compact-scatter + expand
    # replacing the left gather, then the full windowed join
    platform = jax.devices()[0].platform
    w_impl = "windowed" if platform == "tpu" else "windowed_interp"

    def _lw(a, b, v, w):
        # the windowed emit computes its own compacted repeat internally,
        # so this stage is probe + (compact scatter + expand + right gather)
        lo, cnt, r_order, _rc = probe_only(a, b)
        from cylon_tpu.ops.gather import pack_gather

        r_sorted, _ = pack_gather([(b, None), (w, None)], r_order)
        r_sorted = [(d, None) for d, _v in r_sorted]
        out_cols, n_out = _j._emit_inner_left(
            lo, cnt, [(a, None), (v, None)],
            r_sorted, jnp.int32(n), _j.INNER, cap, n, w_impl,
        )
        return chk(*[d for d, _ in out_cols]) + n_out.astype(jnp.float32)

    stages["probe+windowed_emit"] = jax.jit(_lw)

    def full_windowed(a, b, v, w):
        out, total, shadow = _j.spec_join(
            [(a, None)], [(b, None)],
            [(a, None), (v, None)], [(b, None), (w, None)],
            jnp.int32(n), jnp.int32(n), _j.INNER, cap, w_impl,
        )
        return chk(*[d for d, _ in out]) + total.astype(jnp.float32)

    stages["full_windowed"] = jax.jit(full_windowed)

    for name, fn in stages.items():
        t0 = time.perf_counter()
        float(fn(lk, rk, lv, rv))
        compile_s = time.perf_counter() - t0
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            float(fn(lk, rk, lv, rv))
            best = min(best, time.perf_counter() - t0)
        print(
            json.dumps(
                {
                    "stage": name,
                    "rows": n,
                    "cap": cap,
                    "warm_s": round(best, 4),
                    "compile_s": round(compile_s, 2),
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
