"""Scale proof above 16M rows (VERDICT r4 item 7 / BASELINE config 3).

The reference's published numbers are 200M-row joins over 160 workers and
a 1B-row distributed sort (BASELINE.md); the largest cylon_tpu measurement
anywhere was 16M rows/side. This bench runs, on whatever backend is
reachable (host RAM bounds it, not HBM — the out-of-core join exists for
exactly this):

1. distributed sort at --sort-rows (default 250M; 1B with --sort-rows
   1000000000) over the widest mesh, sample-sort shuffle, fenced;
2. out-of-core join at --join-rows per side (default 100M) streamed
   through bounded device memory in --buckets Grace buckets, with the
   per-phase cost split (spill fetch / stage upload / join / drain fetch)
   and peak-RSS residency evidence.

One JSON line per row, like run_bench. Peak RSS comes from
resource.getrusage(RUSAGE_SELF).ru_maxrss (KiB on Linux).

Usage: python benchmarks/scale_bench.py [--sort-rows N] [--join-rows N]
       [--cpu] [--mesh 8] [--reps 1] [--skip-sort] [--skip-join]
"""
from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("CYLON_TPU_NO_X64", "1")

import numpy as np


def emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


def rss_gb() -> float:
    return round(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6, 2)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sort-rows", type=int, default=250_000_000)
    ap.add_argument("--join-rows", type=int, default=100_000_000,
                    help="rows PER SIDE for the out-of-core join")
    ap.add_argument("--buckets", type=int, default=32)
    ap.add_argument("--chunks", type=int, default=32)
    ap.add_argument("--reps", type=int, default=1)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--mesh", type=int, default=8)
    ap.add_argument("--skip-sort", action="store_true")
    ap.add_argument("--skip-join", action="store_true")
    args = ap.parse_args()

    import __graft_entry__ as ge

    use_cpu = args.cpu
    if not use_cpu:
        import bench as _b

        use_cpu = not _b.probe_tpu(
            float(os.environ.get("BENCH_INIT_TIMEOUT", 120)),
            int(os.environ.get("BENCH_INIT_TRIES", 2)),
        )
    if use_cpu:
        ge._force_cpu_mesh(args.mesh)

    import jax

    import cylon_tpu as ct
    from bench import fence as _sync

    devices = jax.devices()
    platform = devices[0].platform
    world = len(devices) if use_cpu else 1
    ctx = ct.CylonContext.init_distributed(
        ct.TPUConfig(devices=devices[:world])
    )

    # ---- 1. big distributed sort (BASELINE config 3) -------------------
    if not args.skip_sort:
        n = args.sort_rows
        rng = np.random.default_rng(0)
        t0 = time.perf_counter()
        # generate in slabs to keep the host copy transient
        key = rng.integers(-(2**31), 2**31, n, dtype=np.int64).astype(
            np.int32
        )
        tbl = ct.Table.from_pydict(ctx, {"k": key})
        gen_s = time.perf_counter() - t0
        del key
        t0 = time.perf_counter()
        out = tbl.distributed_sort("k")
        _sync(out)
        first_s = time.perf_counter() - t0
        best = first_s
        for _ in range(max(0, args.reps - 1)):
            t0 = time.perf_counter()
            out = tbl.distributed_sort("k")
            _sync(out)
            best = min(best, time.perf_counter() - t0)
        # verify global order on the REAL layout: the sorted table is
        # range-partitioned across shards, each shard front-packed into a
        # cap-sized segment — check per-shard live-prefix monotonicity plus
        # shard-boundary order (one host fetch of the column)
        d = np.asarray(out._columns["k"].data)
        counts = np.asarray(out.counts_dev)
        cap = d.shape[0] // world
        segs = [d[i * cap : i * cap + counts[i]] for i in range(world)]
        mono = all((np.diff(s) >= 0).all() for s in segs)
        nonempty = [s for s in segs if len(s)]
        mono = mono and all(
            nonempty[i][-1] <= nonempty[i + 1][0]
            for i in range(len(nonempty) - 1)
        )
        emit({
            "benchmark": "scale_distributed_sort",
            "platform": platform,
            "world": world,
            "rows": n,
            "warm_s": round(best, 2),
            "first_s": round(first_s, 2),
            "gen_s": round(gen_s, 2),
            "rows_per_sec": round(n / best),
            "sorted_ok": mono,
            "peak_rss_gb": rss_gb(),
        })
        del tbl, out

    # ---- 2. out-of-core join at >=100M rows/side -----------------------
    if not args.skip_join:
        from cylon_tpu.parallel.ooc import OutOfCoreJoin

        n = args.join_rows
        chunk = max(n // args.chunks, 1)
        rng = np.random.default_rng(1)
        # chunk GENERATORS: the whole point is bounded residency — no
        # materialized 100M-row host array outside the streamed chunks
        def chunks(seed, vname):
            r = np.random.default_rng(seed)
            for _ in range(args.chunks):
                m = chunk
                yield {
                    "k": r.integers(0, n, m).astype(np.int32),
                    vname: r.normal(size=m).astype(np.float32),
                }

        t0 = time.perf_counter()
        job = OutOfCoreJoin(
            ctx, on="k", how="inner", num_buckets=args.buckets
        )
        sink = job.execute(chunks(2, "v"), chunks(3, "w"))
        wall = time.perf_counter() - t0
        emit({
            "benchmark": "scale_ooc_join",
            "platform": platform,
            "world": world,
            "rows": 2 * n,
            "rows_out": int(sink.rows),
            "chunks": args.chunks,
            "buckets": args.buckets,
            "wall_s": round(wall, 2),
            "rows_per_sec": round(2 * n / wall),
            "peak_rss_gb": rss_gb(),
            **{k: round(v, 2) for k, v in job.cost_split.items()},
        })


if __name__ == "__main__":
    main()
