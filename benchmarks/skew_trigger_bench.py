"""Skew-trigger autotune benchmark (ISSUE 15 / ROADMAP-4).

Demonstrates the straggler-driven ``skew_trigger`` decision on a
MILDLY-skewed shape (~2.2x hot/mean) — the band the static 4x-mean
trigger ignores: under ``CYLON_TPU_PROF`` the stage clocks journal a
per-shard straggler ratio into the observation store, the feedback
re-coster flips ``Decisions.skew_trigger`` to 2x-mean (one recompile),
and the relay then sheds the hot bucket's padded collective slots.

Reported per regime (static trigger vs tuned):

- shipped bytes per query: collective payload + the host-relay tail
  (the adaptive plan is charged for BOTH, same accounting as
  ``benchmarks/spill_bench.py``'s skew gate);
- the measured straggler ratio (``prof.straggler_ratio``);
- result equality against the ``CYLON_TPU_NO_AUTOTUNE=1`` oracle.

Under ``--smoke``, exits 1 unless the tuned regime ships STRICTLY fewer
bytes than the static trigger on this shape with oracle-identical rows
and exactly one recompile per decision flip.

Usage:
  python benchmarks/skew_trigger_bench.py --rows 24000 --smoke
  python benchmarks/skew_trigger_bench.py --rows 200000   # report only
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("CYLON_TPU_NO_X64", "1")

import __graft_entry__ as ge

DEVICES = ge._force_cpu_mesh(8)

import numpy as np

import cylon_tpu as ct
from cylon_tpu.utils.tracing import get_count, get_trace_report


def _shipped_bytes() -> int:
    rep = get_trace_report()
    return int(
        rep.get("shuffle.exchanged_bytes", {}).get("rows", 0)
        + rep.get("shuffle.spill.relay_bytes", {}).get("rows", 0)
    )


def _canon(t):
    df = t.to_pandas()
    return df.sort_values(list(df.columns)).reset_index(drop=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=24_000)
    ap.add_argument("--world", type=int, default=8)
    ap.add_argument("--warmup", type=int, default=10,
                    help="collects to run while the evidence accumulates "
                    "(hysteresis depth 2 -> the flip lands well inside)")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()

    obs_dir = tempfile.mkdtemp(prefix="skew_trigger_obs_")
    os.environ["CYLON_TPU_OBS_DIR"] = obs_dir
    os.environ["CYLON_TPU_PROF"] = "1"
    os.environ["CYLON_TPU_AUTOTUNE_MIN_OBS"] = "2"

    ctx = ct.CylonContext.init_distributed(
        ct.TPUConfig(devices=DEVICES[: args.world])
    )
    rng = np.random.default_rng(7)
    n = args.rows
    # ~3.1x hot/mean at world=8 (hot/mean = 7x+1 for a shared fraction
    # x): 30% of rows share one key, permuted so every source shard
    # holds the same mix (block placement would read the full 8x)
    nh = int(n * 0.3)
    keys = rng.permutation(np.concatenate([
        np.zeros(nh, np.int32),
        rng.integers(1, n // 3, n - nh).astype(np.int32),
    ]))
    lt = ct.Table.from_pydict(
        ctx, {"k": keys, "v": rng.random(n).astype(np.float32)}
    )
    rt = ct.Table.from_pydict(
        ctx, {"rk": keys.copy(), "w": rng.random(n).astype(np.float32)}
    )
    lf = (
        lt.lazy()
        .join(rt.lazy(), left_on="k", right_on="rk", how="inner")
        .groupby("k", {"v": "sum"})
    )

    m0 = get_count("plan.cache.miss")
    per_run = []
    for _ in range(args.warmup):
        b0 = _shipped_bytes()
        res = lf.collect()
        per_run.append(_shipped_bytes() - b0)
    misses = get_count("plan.cache.miss") - m0

    from cylon_tpu.obs import store as obstore
    from cylon_tpu.plan import feedback as fb
    from cylon_tpu.utils.tracing import report

    s = obstore.store()
    prof = next(
        (p for p in s.profiles.values()
         if p.get("dec", {}).get("skew_trigger") is not None),
        None,
    )
    flips = sum(p.get("flips", 0) for p in s.profiles.values())
    strag = report("prof.").get("prof.straggler_ratio", {}).get("last")

    b0 = _shipped_bytes()
    tuned_res = _canon(lf.collect())
    tuned_bytes = _shipped_bytes() - b0
    with fb.autotune_disabled():
        b0 = _shipped_bytes()
        static_res = _canon(lf.collect())
        static_bytes = _shipped_bytes() - b0

    hot = prof["hot"] if prof else 0
    mean = max(prof["mean_bucket"], 1) if prof else 1
    print(f"# shape: {n} rows, world={args.world}, "
          f"hot/mean {hot / mean:.2f}x, measured straggler "
          f"{strag if strag is not None else float('nan'):.2f}")
    print(f"# decision: skew_trigger="
          f"{prof['dec']['skew_trigger'] if prof else None} "
          f"(static {4}x-mean), flips={flips}, "
          f"plan-cache misses={misses} (pin: 1 + flips)")
    print(f"# bytes/query over warm-up: {per_run}")
    print(f"# static trigger: {static_bytes} B/query   "
          f"tuned trigger: {tuned_bytes} B/query   "
          f"({1 - tuned_bytes / max(static_bytes, 1):.0%} fewer)")
    identical = (
        static_res.shape == tuned_res.shape
        and np.array_equal(
            static_res["k"].to_numpy(), tuned_res["k"].to_numpy()
        )
        and np.allclose(
            static_res[static_res.columns[-1]].to_numpy(),
            tuned_res[tuned_res.columns[-1]].to_numpy(),
        )
    )
    print(f"# oracle-identical: {identical}")
    _ = res

    if args.smoke:
        if prof is None or prof["dec"].get("skew_trigger") is None:
            print("SKEW TRIGGER SMOKE FAIL: decision never flipped",
                  file=sys.stderr)
            return 1
        if misses != 1 + flips:
            print(f"SKEW TRIGGER SMOKE FAIL: {misses} plan-cache misses "
                  f"!= 1 + {flips} flips", file=sys.stderr)
            return 1
        if not tuned_bytes < static_bytes:
            print(f"SKEW TRIGGER SMOKE FAIL: tuned {tuned_bytes} B >= "
                  f"static {static_bytes} B", file=sys.stderr)
            return 1
        if not identical:
            print("SKEW TRIGGER SMOKE FAIL: tuned result differs from "
                  "the CYLON_TPU_NO_AUTOTUNE oracle", file=sys.stderr)
            return 1
        print("# skew trigger smoke ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
