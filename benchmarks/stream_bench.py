"""Incremental-view refresh benchmark: delta refresh vs full recompute.

The ISSUE-16 measurement, on the headline q3 shape (big appendable left
join small static right -> groupby-SUM): after warming BOTH paths, each
round appends 1% new left rows and times

refresh (incremental)
    ``IncrementalView.refresh()`` — the delta rides the ordinary
    shuffle machinery (dL join R + mergeable-partial groupby merge),
    generation-keyed so nothing aliases the full path's caches.
full recompute
    the ``CYLON_TPU_NO_IVM=1`` differential oracle — a fresh view over
    the SAME generation's snapshots, full join + groupby.

Payloads are integer-valued f32 (sums associate exactly), so the gate
demands EXACT canonicalized equality between the two results every
round — a lossy refresh cannot buy its speedup.

``--smoke`` gates (CI job ``stream-smoke``):

- incremental refresh at 1% append >= 5x faster than full recompute
  (ratio of medians over the measured rounds);
- exact oracle equality in every round.

Usage::

    python benchmarks/stream_bench.py --smoke --out stream_bench.json
    python benchmarks/stream_bench.py --rows 400000 --rounds 5 --world 4
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as ge

DEVICES = ge._force_cpu_mesh(8)

import numpy as np

import cylon_tpu as ct
from cylon_tpu import stream


def canon(t):
    d = t.to_pydict()
    cols = sorted(d)
    return cols, sorted(zip(*(d[c] for c in cols)))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=200_000,
                    help="appendable left-side rows (right side is "
                         "rows//32, static)")
    ap.add_argument("--world", type=int, default=4)
    ap.add_argument("--rounds", type=int, default=3,
                    help="measured append+refresh rounds (after 1 warm)")
    ap.add_argument("--append-frac", type=float, default=0.01)
    ap.add_argument("--keyspace", type=int, default=512)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--smoke", action="store_true",
                    help="gate: >=5x refresh speedup + exact oracle "
                         "equality every round")
    ap.add_argument("--out", default=None, help="write a JSON report")
    args = ap.parse_args()

    ctx = ct.CylonContext.init_distributed(
        ct.TPUConfig(devices=DEVICES[: args.world])
    )
    rng = np.random.default_rng(args.seed)

    def lbatch(n):
        return {"k": rng.integers(0, args.keyspace, n).astype(np.int32),
                "v": rng.integers(-50, 50, n).astype(np.float32)}

    left = stream.AppendableTable(ctx, lbatch(args.rows))
    n_r = max(args.rows // 32, 256)
    right = ct.Table.from_pydict(ctx, {
        "rk": rng.integers(0, args.keyspace, n_r).astype(np.int32),
        "w": rng.integers(-50, 50, n_r).astype(np.float32),
    })

    def build(lt):
        return (
            lt.lazy()
            .join(right.lazy(), left_on="k", right_on="rk")
            .groupby("k", {"v": "sum"})
        )

    d_rows = max(int(args.rows * args.append_frac), 1)
    v = stream.view(build, left)

    # warm BOTH paths: initial full compute, one incremental round, one
    # oracle recompute — every kernel shape bucket both paths touch is
    # compiled before a single measured clock starts
    v.refresh()
    left.append(lbatch(d_rows))
    v.refresh()
    with stream.ivm_disabled():
        stream.view(build, left).refresh()
    assert v.stats["inc"] == 1, f"warm round was not incremental: {v.stats}"

    inc_s, full_s = [], []
    for r in range(args.rounds):
        left.append(lbatch(d_rows))
        t0 = time.perf_counter()
        got = v.refresh()
        inc_s.append(time.perf_counter() - t0)
        with stream.ivm_disabled():
            t0 = time.perf_counter()
            want = stream.view(build, left).refresh()
            full_s.append(time.perf_counter() - t0)
        if canon(got) != canon(want):
            print(f"STREAM BENCH FAIL: round {r} incremental result != "
                  "full-recompute oracle", file=sys.stderr)
            return 1
        print(f"[stream] round {r}: inc {inc_s[-1] * 1e3:.1f} ms  "
              f"full {full_s[-1] * 1e3:.1f} ms  "
              f"(delta {d_rows} rows over {left.row_count})")

    med_inc = float(np.median(inc_s))
    med_full = float(np.median(full_s))
    speedup = med_full / max(med_inc, 1e-9)
    report = {
        "rows": args.rows, "right_rows": n_r, "world": args.world,
        "delta_rows": d_rows, "rounds": args.rounds,
        "inc_s": inc_s, "full_s": full_s,
        "median_inc_s": med_inc, "median_full_s": med_full,
        "speedup": speedup, "stats": dict(v.stats),
        "oracle_equal": True,
    }
    print(f"[stream] refresh-at-{args.append_frac:.0%}-append: "
          f"inc {med_inc * 1e3:.1f} ms vs full {med_full * 1e3:.1f} ms "
          f"-> {speedup:.1f}x (oracle exact-equal all rounds)")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    if args.smoke and speedup < 5.0:
        print(f"STREAM BENCH FAIL: incremental refresh speedup "
              f"{speedup:.2f}x < 5x gate", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
