"""High-cardinality string-key join, end to end (VERDICT r3 item 6).

Measures the three phases the 10B-row north star cares about separately —
ingest (host string encode: np.unique per table), dictionary unification
(union of two sorted dictionaries + device code remap), and the join kernel
itself — so the host-vs-device cost split is explicit. The dictionary union
runs through the native two-pointer merge (native/runtime.cpp
ct_dict_union_u32) when available; CYLON_TPU_NO_NATIVE=1 re-runs it through
np.union1d for the A/B.

Reference analog: BinaryHashPartitionKernel hashes raw strings per row
(arrow/arrow_partition_kernels.cpp:243-305) — here strings become
order-preserving int32 codes once at ingest and every kernel is integer.

Usage: python benchmarks/string_join_bench.py [--rows N] [--card C] [--cpu]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("CYLON_TPU_NO_X64", "1")

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=16_000_000)
    ap.add_argument("--card", type=int, default=0,
                    help="key cardinality per side (default rows//2)")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import __graft_entry__ as ge

    use_cpu = args.cpu
    if not use_cpu:
        import bench as _b

        use_cpu = not _b.probe_tpu(
            float(os.environ.get("BENCH_INIT_TIMEOUT", 120)),
            int(os.environ.get("BENCH_INIT_TRIES", 2)),
        )
    if use_cpu:
        ge._force_cpu_mesh(1)
        args.rows = min(args.rows, 1_000_000)

    import jax

    import cylon_tpu as ct
    from bench import fence
    from cylon_tpu import native
    from cylon_tpu.table import _unify_dict_pair

    platform = jax.devices()[0].platform
    n = args.rows
    card = args.card or n // 2
    rng = np.random.default_rng(0)

    # distinct-per-side key universes with ~50% overlap: the union is real
    # work (neither side's dictionary contains the other)
    def keys(offset):
        ints = rng.integers(0, 2 * card, n) + offset
        return np.char.add("k", ints.astype("U16"))

    lk_host = keys(0)
    rk_host = keys(card)

    ctx = ct.CylonContext.init()

    # --- phase 1: ingest (host encode: np.unique -> sorted dict + codes) ---
    t0 = time.perf_counter()
    left = ct.Table.from_pydict(
        ctx, {"k": lk_host, "v": rng.normal(size=n).astype(np.float32)}
    )
    right = ct.Table.from_pydict(
        ctx, {"k": rk_host, "w": rng.normal(size=n).astype(np.float32)}
    )
    fence(left)
    fence(right)
    ingest_s = time.perf_counter() - t0
    da = len(left.column("k").dictionary)
    db = len(right.column("k").dictionary)

    # --- phase 2: dictionary unification (host union + device remap) ---
    t0 = time.perf_counter()
    lu, ru = _unify_dict_pair(left, right, ["k"], ["k"])
    fence(lu)
    fence(ru)
    unify_s = time.perf_counter() - t0

    # --- phase 3: the join itself on pre-unified tables ---
    def join():
        out = lu.join(ru, on="k", how="inner")
        fence(out)
        return out

    t0 = time.perf_counter()
    out = join()
    compile_s = time.perf_counter() - t0
    best = float("inf")
    for _ in range(args.reps):
        t0 = time.perf_counter()
        out = join()
        best = min(best, time.perf_counter() - t0)

    print(json.dumps({
        "benchmark": "string_key_join",
        "rows": 2 * n, "dict_a": int(da), "dict_b": int(db),
        "platform": platform,
        "native_union": bool(native.available()),
        "ingest_s": round(ingest_s, 3),
        "unify_s": round(unify_s, 3),
        "join_warm_s": round(best, 4),
        "join_compile_s": round(compile_s, 2),
        "join_rows": int(out.row_count),
        "end_to_end_rows_per_sec": round(
            2 * n / (ingest_s + unify_s + best)
        ),
        "join_rows_per_sec": round(2 * n / best),
    }), flush=True)


if __name__ == "__main__":
    main()
