"""Head-to-head: Pallas PK-FK probe vs the sort-based spec_join
(VERDICT round-2 item 6).

Same inputs (unique right keys — the PK-FK shape the reference's own
benchmark generator produces with keyspace = n), same semantics (inner
join emit of matched row-index pairs). Prints one JSON line per
implementation; on TPU the pallas kernel compiles to Mosaic, on CPU it
runs in interpret mode (correctness smoke only — interpret is not a
performance mode, the line is marked).

Usage: python benchmarks/pallas_bench.py [--rows N] [--cpu] [--bucket B]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("CYLON_TPU_NO_X64", "1")

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=4_000_000)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--bucket", type=int, default=256)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()

    import __graft_entry__ as ge

    use_cpu = args.cpu
    if not use_cpu:
        import bench as _b

        use_cpu = not _b.probe_tpu(
            float(os.environ.get("BENCH_INIT_TIMEOUT", 120)),
            int(os.environ.get("BENCH_INIT_TRIES", 2)),
        )
    if use_cpu:
        ge._force_cpu_mesh(1)
        args.rows = min(args.rows, 100_000)  # interpret mode is slow

    import jax
    import jax.numpy as jnp

    from bench import fence  # noqa: F401 (import sets the compile cache env)
    from cylon_tpu.ops import join as _j
    from cylon_tpu.ops.pallas_join import pk_inner_join

    platform = jax.devices()[0].platform
    interpret = platform == "cpu"
    n = args.rows
    rng = np.random.default_rng(0)
    r_key = rng.permutation(np.arange(2 * n, dtype=np.int32))[:n]  # unique PK
    l_key = rng.choice(r_key, size=n, replace=True)  # FK, all hit

    lk = jnp.asarray(l_key)
    rk = jnp.asarray(r_key)
    nl = jnp.int32(n)
    nr = jnp.int32(n)

    def timed(fn, label, extra=None):
        t0 = time.perf_counter()
        out = fn()
        # dependent-scalar fetch: the only trustworthy fence via the tunnel
        total = int(np.asarray(out[2]))
        compile_s = time.perf_counter() - t0
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.perf_counter()
            out = fn()
            total = int(np.asarray(out[2]))
            best = min(best, time.perf_counter() - t0)
        print(json.dumps({
            "benchmark": label,
            "rows": 2 * n,
            "platform": platform,
            "warm_s": round(best, 4),
            "compile_s": round(compile_s, 2),
            "rows_per_sec": round(2 * n / best),
            "join_rows": total,
            **(extra or {}),
        }), flush=True)
        return total

    # -- sort-based spec_join (the production path) --
    cap_out = 1 << (2 * n - 1).bit_length()

    @jax.jit
    def sort_join():
        out, total, _shadow = _j.spec_join(
            [(lk, None)], [(rk, None)],
            [(lk, None)], [(rk, None)],
            nl, nr, _j.INNER, cap_out,
        )
        return out, None, total

    t_sort = timed(sort_join, "pk_join_sort_based")

    # -- pallas bucketed probe --
    def pallas_join():
        l_idx, r_idx, total, bad = pk_inner_join(
            lk, rk, nl, nr, B=args.bucket, interpret=interpret,
        )
        return (l_idx, r_idx), total, bad

    def pallas_wrapped():
        (li, ri), total, bad = pallas_join()
        assert int(np.asarray(bad)) == 0, "speculation miss (fallback case)"
        return (li, ri), None, total

    t_pal = timed(
        pallas_wrapped, "pk_join_pallas_bucketed",
        {"bucket": args.bucket, "interpret": interpret},
    )
    assert t_sort == t_pal, (t_sort, t_pal)


if __name__ == "__main__":
    main()
