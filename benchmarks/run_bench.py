"""Benchmark suite + scaling harness.

The analog of the reference's benchmark drivers and scaling orchestrator
(cpp/src/examples/bench/table_join_dist_test.cpp — per-rank join timing;
cpp/src/experiments/run_dist_scaling.py:9-40 — weak/strong scaling sweeps;
python/examples/op_benchmark/*.py — per-op micro-benchmarks).

Covers BASELINE.md's benchmark configs:
  1. local inner join (single shard)
  2. distributed join + groupby aggregate (TPC-H Q3-style) over a mesh
  3. distributed sort (sample-sort shuffle)
  4. set ops (union/subtract/intersect) with hash repartition
plus weak/strong scaling of the distributed join over mesh size.

Usage:
  python benchmarks/run_bench.py                 # full suite on best backend
  python benchmarks/run_bench.py --rows 2000000  # scale problem size
  python benchmarks/run_bench.py --cpu           # force host-CPU backend
  python benchmarks/run_bench.py --scaling       # add the mesh-size sweep
  python benchmarks/run_bench.py --out BENCH.md  # write the markdown table

Each result prints as a JSON line; --out also renders a markdown table.
On CPU the mesh is virtual (xla_force_host_platform_device_count), so
"scaling" measures sharding overhead, not real ICI speedup — the numbers
are still the regression baseline the real-TPU run is compared against.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("CYLON_TPU_NO_X64", "1")

import numpy as np

BASELINE_JOIN_ROWS_PER_SEC = 400e6 / 141.5  # reference 1-worker rate



def _vs_baseline(work_rows: int, seconds: float, world: int) -> float:
    """Per-chip rate vs the reference's published 1-worker rate — the ONE
    definition every bench row's vs_baseline cell uses."""
    return round(work_rows / seconds / BASELINE_JOIN_ROWS_PER_SEC / max(world, 1), 3)


def _bench(fn, reps: int):
    """(best wall seconds, first-call seconds [compile], warm samples).

    The per-rep samples feed the obs.metrics latency histograms (the
    serving substrate, ISSUE 8) so every BENCH row carries p50/p99
    columns from the SAME histogram implementation the plan-fingerprint
    registry uses — quantiles over the warm reps, compile excluded."""
    t0 = time.perf_counter()
    fn()
    compile_s = time.perf_counter() - t0
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        samples.append(time.perf_counter() - t0)
    return (min(samples) if samples else float("inf")), compile_s, samples


# the ONE tunnel-safe completion fence (dependent-scalar fetch; see its
# docstring for why block_until_ready cannot be trusted here)
from bench import fence as _sync  # noqa: E402


def _roofline_recorded(extra: dict, hbm: float, measured_s: float, op) -> None:
    """%membw for an EAGER op chain: record every kernel dispatch during one
    warm call (engine.record_kernels) and sum the traced models — the model
    covers exactly the programs the op executed.

    Collective-volume accounting (collectives / collective_mb) is attached
    even with hbm<=0: the traced byte counts are platform-independent, and
    per-world collective volume is the quantity that predicts real ICI
    scaling from a virtual-CPU-mesh run. Only the bandwidth-relative
    numbers (model_s, pct_membw) need the real chip's hbm."""
    try:
        from benchmarks.roofline import Report, analyze, model_seconds, pct_membw
        from cylon_tpu import engine

        engine.record_kernels(True)
        try:
            op()
        finally:
            kernels = engine.recorded_kernels()
            engine.record_kernels(False)
        if not kernels:
            return
        total = Report()
        for fn, args in kernels:
            rep = analyze(fn, *args)
            total.sort_count += rep.sort_count
            total.sort_bytes_per_pass += rep.sort_bytes_per_pass
            total.sort_pass_bytes += rep.sort_pass_bytes
            total.sort_passes += rep.sort_passes
            total.radix_passes += rep.radix_passes
            total.radix_pass_bytes += rep.radix_pass_bytes
            total.gather_bytes += rep.gather_bytes
            total.scatter_bytes += rep.scatter_bytes
            total.elementwise_bytes += rep.elementwise_bytes
            total.collective_bytes += rep.collective_bytes
            total.collective_count += rep.collective_count
        if hbm > 0:
            extra["model_s"] = round(model_seconds(total, hbm), 4)
            extra["pct_membw"] = round(
                100 * pct_membw(total, measured_s, hbm), 1
            )
        extra["kernels"] = len(kernels)
        # bytes-over-ICI accounting (per op): the collective volume the
        # op ships across the mesh + how many collectives it issues
        extra["collectives"] = total.collective_count
        extra["collective_mb"] = round(total.collective_bytes / 1e6, 2)
        if total.sort_pass_bytes:
            extra["sort_passes_bytes_gb"] = round(total.sort_pass_bytes / 1e9, 2)
        if total.sort_passes:
            # traced pass census: radix histogram passes count 1 apiece,
            # bitonic networks k(k+1)/2 — the column the radix engine's
            # CI gate (tools/sort_smoke.py) reads
            extra["sort_passes"] = round(total.sort_passes, 1)
    except Exception as e:
        print(f"# roofline(recorded) failed: {e}", file=sys.stderr)


def _roofline(extra: dict, hbm: float, measured_s: float, fn, *args) -> None:
    """Attach model_s / pct_membw for a traced program to a record's extras.
    The traced (fn, args) MUST reproduce the measured path's exact
    capacities — a different cap models a different kernel.

    Collective accounting (collectives / collective_mb) is attached even
    with hbm<=0, exactly like :func:`_roofline_recorded` — the fused
    single-program rows (dist_inner_join_fused / q3_fused) previously left
    their BENCH.md colls / coll MB cells blank because only the
    bandwidth-relative numbers were gated on a real chip's hbm."""
    try:
        from benchmarks.roofline import analyze, model_seconds, pct_membw

        rep = analyze(fn, *args)
        extra["collectives"] = rep.collective_count
        extra["collective_mb"] = round(rep.collective_bytes / 1e6, 2)
        if hbm > 0:
            extra["model_s"] = round(model_seconds(rep, hbm), 4)
            extra["pct_membw"] = round(100 * pct_membw(rep, measured_s, hbm), 1)
        if rep.sort_pass_bytes:
            extra["sort_passes_bytes_gb"] = round(rep.sort_pass_bytes / 1e9, 2)
        if rep.sort_passes:
            extra["sort_passes"] = round(rep.sort_passes, 1)
    except Exception as e:  # the model must never sink the bench
        print(f"# roofline failed: {e}", file=sys.stderr)


def make_tables(ct, ctx, n, keyspace, seed=0):
    rng = np.random.default_rng(seed)
    left = ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(0, keyspace, n).astype(np.int32),
         "v": rng.normal(size=n).astype(np.float32)},
    )
    right = ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(0, keyspace, n).astype(np.int32),
         "w": rng.normal(size=n).astype(np.float32)},
    )
    return left, right


def run_suite(n_rows: int, reps: int, mesh_devices, scaling: bool):
    import jax

    import cylon_tpu as ct

    results = []

    # host-core normalization (VERDICT r4 weak point 5): the CPU regression
    # baseline broke when the host dropped to one physical core — a per-core
    # rate survives host resizing, so round-over-round CPU comparisons read
    # this column, not wall time
    ncores = os.cpu_count() or 1
    is_cpu = mesh_devices[0].platform == "cpu"

    from cylon_tpu.obs import metrics as _obs_metrics

    def record(name, seconds, compile_s, work_rows, world, extra=None,
               samples=None):
        # warm-rep latency quantiles through the obs.metrics histogram
        # registry (keyed like a serving fingerprint: one distribution
        # per row+world) — rows that measure through the REAL plan
        # fingerprint (q3_lazy) put their own p50/p99 in extra instead
        lat = {}
        if samples:
            key = f"bench:{name}@w{world}"
            for dt in samples:
                _obs_metrics.observe_latency(key, dt, label=name)
            qq = _obs_metrics.latency_quantiles(key)
            lat = {"p50_ms": round(qq["p50_s"] * 1e3, 2),
                   "p99_ms": round(qq["p99_s"] * 1e3, 2)}
        rate = work_rows / seconds
        row = {
            "benchmark": name,
            "rows": work_rows,
            "world": world,
            "warm_s": round(seconds, 4),
            "compile_s": round(compile_s, 2),
            "rows_per_sec": round(rate),
            **({"host_cores": ncores,
                "rows_per_sec_per_core": round(rate / ncores)}
               if is_cpu else {}),
            **lat,
            **(extra or {}),
        }
        results.append(row)
        print(json.dumps(row), flush=True)

    # bandwidth assumption for every roofline row (0 disables the model)
    hbm = float(os.environ.get(
        "BENCH_HBM_GBPS",
        0 if mesh_devices[0].platform == "cpu" else 819.0,
    ))

    # ---- config 1: local inner join, single shard --------------------------
    ctx1 = ct.CylonContext.init_distributed(ct.TPUConfig(devices=mesh_devices[:1]))
    left, right = make_tables(ct, ctx1, n_rows, keyspace=n_rows)

    def local_join():
        out = left.join(right, on="k", how="inner")
        _sync(out)

    s, c, laps = _bench(local_join, reps)
    lj_extra = {"vs_baseline": _vs_baseline(2 * n_rows, s, 1)}
    if hbm > 0:
        import jax as _jax
        import jax.numpy as jnp

        from cylon_tpu.engine import round_cap
        from cylon_tpu.ops import join as _jops

        cap = left.shard_cap
        # the measured call takes the SPECULATIVE path: spec_cap =
        # round_cap(max(cap_l, cap_r)) (table.py speculative block)
        cap_out = round_cap(max(left.shard_cap, right.shard_cap))

        def _lj(lk, lv, rk, rv, nl, nr):
            return _jops.spec_join(
                [(lk, None)], [(rk, None)],
                [(lk, None), (lv, None)], [(rk, None), (rv, None)],
                nl, nr, _jops.INNER, cap_out,
            )[1]

        sds = _jax.ShapeDtypeStruct
        _roofline(
            lj_extra, hbm, s, _lj,
            sds((cap,), jnp.int32), sds((cap,), jnp.float32),
            sds((cap,), jnp.int32), sds((cap,), jnp.float32),
            sds((), jnp.int32), sds((), jnp.int32),
        )
    record("local_inner_join", s, c, 2 * n_rows, 1, lj_extra, samples=laps)

    # ---- the distributed configs over the widest mesh ----------------------
    world = len(mesh_devices)
    ctx = ct.CylonContext.init_distributed(ct.TPUConfig(devices=mesh_devices))
    left, right = make_tables(ct, ctx, n_rows, keyspace=n_rows)

    def dist_join():
        out = left.distributed_join(right, on="k", how="inner")
        _sync(out)

    s, c, laps = _bench(dist_join, reps)
    dj_extra = {"vs_baseline": _vs_baseline(2 * n_rows, s, world)}
    _roofline_recorded(dj_extra, hbm, s, dist_join)
    record("dist_inner_join", s, c, 2 * n_rows, world, dj_extra, samples=laps)

    # config 1a: the same join under the quantized float wire tier
    # (ops/quant.py, CYLON_TPU_QUANT_TOL=1e-2): the f32 payload lanes —
    # the reason this shape DECLINES bit-lossless wire narrowing — ride
    # block-scaled int8 fields, so the coll MB cell is the win
    # (tools/quant_smoke.py holds the CI gate and the error-bound pin)
    prev_qt = os.environ.get("CYLON_TPU_QUANT_TOL")
    os.environ["CYLON_TPU_QUANT_TOL"] = "1e-2"
    try:
        s, c, laps = _bench(dist_join, reps)
        djq_extra = {"vs_baseline": _vs_baseline(2 * n_rows, s, world)}
        _roofline_recorded(djq_extra, hbm, s, dist_join)
        record(
            "dist_inner_join_quant", s, c, 2 * n_rows, world, djq_extra,
            samples=laps,
        )
    finally:
        if prev_qt is None:
            os.environ.pop("CYLON_TPU_QUANT_TOL", None)
        else:
            os.environ["CYLON_TPU_QUANT_TOL"] = prev_qt

    # config 1b: the same join at ~10% selectivity with the semi-join
    # sketch filter (ops/sketch.py): both sides prune provably partnerless
    # rows against the other side's broadcast key sketch before the
    # payload all_to_all — the coll MB cell is the win, the sketch
    # collective's own bytes included (benchmarks/semi_filter_bench.py
    # holds the CI gate and the full selectivity sweep)
    from benchmarks.semi_filter_bench import make_pair as _semi_pair
    from cylon_tpu.ops import sketch as _sk_mod
    from cylon_tpu.utils.tracing import report as _trace_report
    from cylon_tpu.utils.tracing import reset_trace as _treset

    left_s, right_s = _semi_pair(
        ct, ctx, np.random.default_rng(7), n_rows, sel=0.10
    )

    def dist_join_semi():
        out = left_s.distributed_join(right_s, on="k", how="inner")
        _sync(out)

    s, c, laps = _bench(dist_join_semi, reps)
    djs_extra = {"vs_baseline": _vs_baseline(2 * n_rows, s, world)}
    _treset()
    _roofline_recorded(djs_extra, hbm, s, dist_join_semi)
    # the semi-filter gauges of the recorded call ride the bench row so
    # regenerated BENCH tables carry them next to the coll MB they explain
    sf = _trace_report("shuffle.semi_filter.")
    g = sf.get("shuffle.semi_filter.selectivity", {})
    if g.get("count"):
        djs_extra["semi_selectivity"] = round(g["total_s"] / g["count"], 4)
    djs_extra["sketch_mb"] = round(
        _trace_report("semi_filter.").get(
            "semi_filter.sketch_bytes", {}
        ).get("rows", 0) / 1e6,
        3,
    )
    # the unfiltered coll MB of the identical join, for the narrative
    with _sk_mod.disabled():
        off_extra = {}
        _roofline_recorded(off_extra, hbm, s, dist_join_semi)
        if "collective_mb" in off_extra:
            djs_extra["coll_mb_unfiltered"] = off_extra["collective_mb"]
    record("dist_inner_join_semi", s, c, 2 * n_rows, world, djs_extra, samples=laps)

    # fused execution mode: whole shuffle->join chain as ONE XLA program
    # with a single host sync (vs one sync per op phase in eager mode) —
    # the product surface of parallel/pipeline.py. The host_sync counter
    # demonstrates the dispatch reduction.
    from cylon_tpu.utils.tracing import get_count, reset_trace

    def dist_join_fused():
        out = left.distributed_join(right, on="k", how="inner", mode="fused")
        _sync(out)

    s, c, laps = _bench(dist_join_fused, reps)
    reset_trace()
    dist_join()
    eager_syncs = get_count("host_sync")
    reset_trace()
    dist_join_fused()
    fused_syncs = get_count("host_sync")
    djf_extra = {
        "vs_baseline": _vs_baseline(2 * n_rows, s, world),
        "host_syncs": fused_syncs, "host_syncs_eager": eager_syncs,
    }
    # traced even with hbm<=0: the collective cells are platform-free
    from cylon_tpu.engine import round_cap
    from cylon_tpu.ops.join import INNER as _INNER
    from cylon_tpu.parallel import shuffle as _shmod
    from cylon_tpu.parallel.pipeline import make_distributed_join_step

    # reproduce _fused_join's EXACT first-attempt capacities
    # (table.py _fused_join: capacity_factor=2.0, respill=1, and the
    # byte-budget clamp of the chunked engine)
    cap = max(left.shard_cap, right.shard_cap)
    respill = 1
    bucket_cap = round_cap(int(2.0 * cap / max(world, 1)))
    if world > 1:
        row_bytes = max(
            _shmod.exchange_row_bytes(left._flat_cols()),
            _shmod.exchange_row_bytes(right._flat_cols()),
        )
        bucket_cap = min(
            bucket_cap,
            _shmod.budget_bucket_cap(
                row_bytes, world, ctx.shuffle_byte_budget, bucket_cap
            ),
        )
        join_cap = round_cap(2 * (1 + respill) * world * bucket_cap)
    else:
        join_cap = round_cap(left.shard_cap + right.shard_cap)
    js = make_distributed_join_step(
        ctx.mesh, ctx.axis_name, (0,), (0,), _INNER,
        bucket_cap=bucket_cap, join_cap=join_cap, respill=respill,
    )
    _roofline(
        djf_extra, hbm, s, js,
        (left._flat_cols(), left.counts_dev,
         right._flat_cols(), right.counts_dev), (),
    )
    record("dist_inner_join_fused", s, c, 2 * n_rows, world, djf_extra, samples=laps)

    # config 2: join + groupby aggregate (TPC-H Q3-ish)
    def q3():
        out = left.distributed_join(right, on="k", how="inner")
        g = out.distributed_groupby("k_x", {"v": "sum"})
        _sync(g)

    s, c, laps = _bench(q3, reps)
    q3_extra = {"vs_baseline": _vs_baseline(2 * n_rows, s, world)}
    _roofline_recorded(q3_extra, hbm, s, q3)
    record("dist_join_groupby_q3", s, c, 2 * n_rows, world, q3_extra, samples=laps)

    # config 2a': the same chain with order propagation — the join emits
    # grouped-key order (emit_order='key', same kernel cost) and the
    # groupby's factorize lexsort elides into a run-detect; the sort GB
    # column is the measured win (benchmarks/ordering_bench.py gates it)
    def q3_ordered():
        out = left.distributed_join(right, on="k", how="inner",
                                    emit_order="key")
        g = out.distributed_groupby("k_x", {"v": "sum"})
        _sync(g)

    s, c, laps = _bench(q3_ordered, reps)
    q3o_extra = {"vs_baseline": _vs_baseline(2 * n_rows, s, world)}
    _roofline_recorded(q3o_extra, hbm, s, q3_ordered)
    record("dist_join_groupby_q3_ordered", s, c, 2 * n_rows, world, q3o_extra, samples=laps)

    # config 2a'': the SERVING-substrate row (ISSUE 8): the same q3
    # through the lazy plan layer over the cached executor. Its p50/p99
    # come from the REAL plan-fingerprint histogram that every
    # LazyFrame.dispatch() feeds (end time rides the deferred count
    # materialization) — exactly what the compile-once-serve-many
    # benchmark (ROADMAP 1) will read at scale.
    right_rk = right.rename({"k": "rk"})
    lf_q3 = (
        left.lazy()
        .join(right_rk.lazy(), left_on="k", right_on="rk")
        .groupby("k", {"v": "sum"})
    )

    def q3_lazy():
        lf_q3.collect()

    # compile OUTSIDE the histogram window (its observation is reset
    # away) so hist_count == the warm reps and p50/p99 are warm-query
    # latency only, matching the _bench docstring's contract
    t0 = time.perf_counter()
    q3_lazy()
    c = time.perf_counter() - t0
    _obs_metrics.reset_latency()
    laps = []
    for _ in range(max(reps, 1)):
        t0 = time.perf_counter()
        q3_lazy()
        laps.append(time.perf_counter() - t0)
    s = min(laps)
    rep = _obs_metrics.latency_report()
    fkey, ent = max(rep.items(), key=lambda kv: kv[1]["count"])
    ql_extra = {
        "vs_baseline": _vs_baseline(2 * n_rows, s, world),
        "fingerprint": fkey,
        "hist_count": ent["count"],
        "p50_ms": round(ent["p50_s"] * 1e3, 2),
        "p99_ms": round(ent["p99_s"] * 1e3, 2),
    }
    record("dist_join_groupby_q3_lazy", s, c, 2 * n_rows, world, ql_extra)

    # config 2b: the same chain fully fused (join + groupby + psum in one
    # program, parallel/pipeline.make_join_groupby_step — what the multichip
    # dryrun runs)
    from cylon_tpu.ops.join import INNER
    from cylon_tpu.parallel.pipeline import make_join_groupby_step

    cap = left.shard_cap
    step = make_join_groupby_step(
        ctx.mesh, ctx.axis_name, l_key_idx=(0,), r_key_idx=(0,),
        agg_col_idx=1, how=INNER,
        bucket_cap=max(64, 4 * cap // max(world, 1)),
        join_cap=4 * cap, group_cap=2 * cap,
    )
    lflat = left._flat_cols()
    rflat = right._flat_cols()

    def q3_fused():
        out = step((lflat, left.counts_dev, rflat, right.counts_dev), ())
        jax.block_until_ready(out)
        _ = np.asarray(out[3])  # the single fetch

    s, c, laps = _bench(q3_fused, reps)
    q3f_extra = {
        "vs_baseline": _vs_baseline(2 * n_rows, s, world),
        "host_syncs": 1,
    }
    # roofline (VERDICT round-2 item 2): same `step`, same args as measured
    _roofline(
        q3f_extra, hbm, s, step,
        (lflat, left.counts_dev, rflat, right.counts_dev), (),
    )
    record("dist_join_groupby_q3_fused", s, c, 2 * n_rows, world, q3f_extra, samples=laps)

    # config 3: distributed sort (sample sort)
    def dsort():
        out = left.distributed_sort("k")
        _sync(out)

    s, c, laps = _bench(dsort, reps)
    ds_extra = {"vs_baseline": _vs_baseline(n_rows, s, world)}
    _roofline_recorded(ds_extra, hbm, s, dsort)
    record("dist_sort", s, c, n_rows, world, ds_extra, samples=laps)

    # config 3b: the 3-key narrow-lane local sort (ISSUE 5 lane packing):
    # the packed row vs the kill-switch row is the measured sort-word
    # fusion win in the sort GB column (keys span ~12/~16/~20 bits ->
    # pad + 3 value lanes fuse into ONE uint64 sort word;
    # benchmarks/lane_pack_bench.py holds the CI gate)
    from benchmarks.lane_pack_bench import make_sort_table
    from cylon_tpu.ops import stats as _lp_gate

    mt = make_sort_table(ct, ctx, np.random.default_rng(9), n_rows)

    def msort():
        out = mt.sort(["a", "b", "c"])
        _sync(out)

    # the packed/nopack pair measures the LANE-FUSION win in isolation, so
    # both run on the bitonic network (radix kill-switched) — comparable
    # with every earlier BENCH round; the radix row below is the
    # width-adaptive engine on the same packed table (sort passes column:
    # ceil(42/4)=11-ish histogram passes vs L(L+1)/2 bitonic sweeps)
    from cylon_tpu.ops import radix as _radix_mod

    with _radix_mod.disabled():
        s, c, laps = _bench(msort, reps)
        mp_extra = {}
        _roofline_recorded(mp_extra, hbm, s, msort)
        record("multikey_sort_packed", s, c, n_rows, world, mp_extra,
               samples=laps)
        with _lp_gate.disabled():
            s, c, laps = _bench(msort, reps)
            mn_extra = {}
            _roofline_recorded(mn_extra, hbm, s, msort)
            record("multikey_sort_nopack", s, c, n_rows, world, mn_extra,
                   samples=laps)
    s, c, laps = _bench(msort, reps)
    mr_extra = {}
    _roofline_recorded(mr_extra, hbm, s, msort)
    record("multikey_sort_radix", s, c, n_rows, world, mr_extra, samples=laps)

    # config 4: set ops (shuffle on all columns + sorted dedup) — identical
    # schemas required, so pair ``left`` with a second (k, v) table
    left2, _ = make_tables(ct, ctx, n_rows, keyspace=n_rows, seed=1)
    for name, f in (
        ("dist_union", lambda: left.distributed_union(left2)),
        ("dist_subtract", lambda: left.distributed_subtract(left2)),
        ("dist_intersect", lambda: left.distributed_intersect(left2)),
    ):
        def setop(f=f):
            out = f()
            _sync(out)

        s, c, laps = _bench(setop, reps)
        so_extra = {"vs_baseline": _vs_baseline(2 * n_rows, s, world)}
        _roofline_recorded(so_extra, hbm, s, setop)
        record(name, s, c, 2 * n_rows, world, so_extra, samples=laps)

    # config 5: out-of-core join — both inputs stream through bounded device
    # memory (Grace-style partitioned dag join, parallel/ooc.py; the analog
    # of the reference's byte-chunked streaming shuffle + DisJoinOP)
    from cylon_tpu.parallel.ooc import OutOfCoreJoin

    rng5 = np.random.default_rng(2)
    ooc_n = n_rows
    lk = rng5.integers(0, ooc_n, ooc_n).astype(np.int32)
    lv = rng5.normal(size=ooc_n).astype(np.float32)
    rk = rng5.integers(0, ooc_n, ooc_n).astype(np.int32)
    rv = rng5.normal(size=ooc_n).astype(np.float32)
    chunk_rows = max(ooc_n // 16, 1)

    def chunks(k, v, vname):
        for i in range(0, ooc_n, chunk_rows):
            yield {"k": k[i : i + chunk_rows], vname: v[i : i + chunk_rows]}

    runs = []  # (wall_s, cost_split) per call: split must match the best rep

    def ooc():
        t0 = time.perf_counter()
        job = OutOfCoreJoin(ctx, on="k", how="inner", num_buckets=16)
        sink = job.execute(chunks(lk, lv, "v"), chunks(rk, rv, "w"))
        runs.append((time.perf_counter() - t0, job.cost_split))
        return sink.rows

    s, c, laps = _bench(ooc, max(1, reps - 1))
    # gate_exempt: first-call time here is a full host-bound streaming run
    # (16 spills + 16 joins), not XLA compile tax — the compile gate would
    # misfire on runtime. cost_split: per-phase walls of the BEST rep (the
    # run warm_s describes) — the transfer phases (spill_fetch/drain_fetch)
    # are what a remote tunnel inflates; their share is the tunnel-free
    # projection evidence.
    # runs[0] is the cold/compile call _bench always makes first; the best
    # warm rep's split is the one warm_s describes
    best_split = min(runs[1:], key=lambda t: t[0])[1]
    record("ooc_join_16chunks", s, c, 2 * ooc_n, world,
           {"chunk_rows": chunk_rows, "gate_exempt": True, **best_split},
           samples=laps)

    # ---- scaling sweep: strong scaling of the distributed join -------------
    if scaling and world > 1:
        sizes = [w for w in (1, 2, 4, 8) if w <= world]
        for w in sizes:
            ctxw = ct.CylonContext.init_distributed(
                ct.TPUConfig(devices=mesh_devices[:w])
            )
            lw, rw = make_tables(ct, ctxw, n_rows, keyspace=n_rows)

            def djw():
                out = lw.distributed_join(rw, on="k", how="inner")
                jax.block_until_ready([col.data for col in out._columns.values()])

            s, c, laps = _bench(djw, reps)
            sc_extra = {"vs_baseline": _vs_baseline(2 * n_rows, s, w)}
            _roofline_recorded(sc_extra, hbm, s, djw)
            record("dist_join_strong_scaling", s, c, 2 * n_rows, w, sc_extra, samples=laps)
            # weak scaling: n_rows per shard
            lww, rww = make_tables(ct, ctxw, n_rows * w // max(sizes), keyspace=n_rows)

            def djww():
                out = lww.distributed_join(rww, on="k", how="inner")
                jax.block_until_ready([col.data for col in out._columns.values()])

            s, c, laps = _bench(djww, reps)
            wc_extra = {"vs_baseline": _vs_baseline(2 * len(lww), s, w)}
            _roofline_recorded(wc_extra, hbm, s, djww)
            record("dist_join_weak_scaling", s, c, 2 * len(lww), w, wc_extra, samples=laps)

    return results


def to_markdown(results, header: str) -> str:
    lines = [header, "",
             "| benchmark | world | rows | warm s | p50 ms | p99 ms | compile s | rows/s | rows/s/core | vs_baseline | %membw | colls | coll MB | coll B/row | sort GB | sort passes |",
             "|---|---|---|---|---|---|---|---|---|---|---|---|---|---|---|---|"]
    for r in results:
        # collective volume per world size: the quantity that predicts real
        # ICI scaling (VERDICT r3 weak point 6 — virtual-CPU-mesh wall time
        # does not)
        cmb = r.get("collective_mb", "")
        cbr = (
            round(1e6 * r["collective_mb"] / max(r["rows"], 1), 1)
            if isinstance(cmb, (int, float))
            else ""
        )
        rpc = r.get("rows_per_sec_per_core", "")
        rpc = f"{rpc:,}" if isinstance(rpc, int) else ""
        lines.append(
            f"| {r['benchmark']} | {r['world']} | {r['rows']:,} | {r['warm_s']} "
            # warm-rep latency quantiles from the obs.metrics histograms
            # (the q3_lazy row reads the real plan-fingerprint histogram)
            f"| {r.get('p50_ms', '')} | {r.get('p99_ms', '')} "
            f"| {r['compile_s']} | {r['rows_per_sec']:,} | {rpc} "
            f"| {r.get('vs_baseline', '')} "
            f"| {r.get('pct_membw', '')} | {r.get('collectives', '')} "
            f"| {cmb} | {cbr} "
            # traced sort-pass GB (the TPU wall-time pricing quantity —
            # BENCH.md sliced-join sweep; ordering rows show the elision)
            # + the traced pass census (radix passes count 1, bitonic
            # networks L(L+1)/2 — the multikey radix/packed pair reads
            # the engine's win directly off this column)
            f"| {r.get('sort_passes_bytes_gb', '')} "
            f"| {r.get('sort_passes', '')} |"
        )
    return "\n".join(lines) + "\n"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=int(os.environ.get("BENCH_ROWS", 1_000_000)))
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--cpu", action="store_true", help="force host-CPU backend")
    ap.add_argument("--mesh", type=int, default=8, help="max mesh size (CPU)")
    ap.add_argument("--scaling", action="store_true", help="mesh-size sweep")
    ap.add_argument("--out", type=str, default=None, help="write markdown table")
    ap.add_argument(
        "--compile-gate", type=float,
        default=float(os.environ.get("BENCH_COMPILE_GATE", 30.0)),
        help="fail (exit 1) if any benchmark's compile_s exceeds this many "
             "seconds; <=0 disables. The TPU-tax regression gate "
             "(VERDICT round 2: q3 fused was 165 s).",
    )
    args = ap.parse_args()

    import __graft_entry__ as ge

    use_cpu = args.cpu
    if not use_cpu:
        import bench as _b

        use_cpu = not _b.probe_tpu(
            float(os.environ.get("BENCH_INIT_TIMEOUT", 180)),
            int(os.environ.get("BENCH_INIT_TRIES", 2)),
        )
    if use_cpu:
        devices = ge._force_cpu_mesh(args.mesh)
    else:
        import jax

        devices = jax.devices()

    import jax

    d0 = devices[0]
    print(f"# platform={d0.platform} device={getattr(d0, 'device_kind', '?')} "
          f"mesh={len(devices)}", file=sys.stderr)
    results = run_suite(args.rows, args.reps, devices, args.scaling)
    if args.out:
        hdr = (f"# BENCH — cylon_tpu op suite (platform={d0.platform}, "
               f"mesh={len(devices)}, rows={args.rows:,})")
        # preserve the hand-written trailing narrative across regeneration
        # (the table is generated; the narrative is not). The narrative
        # starts at the first recognized marker — the r4 collective-volume
        # section or the classic "Notes" paragraph.
        notes = ""
        if os.path.exists(args.out):
            with open(args.out) as f:
                prev = f.read()
            starts = [
                i for i in (
                    prev.find("\n**Collective-volume"),
                    prev.find("\nNotes"),
                ) if i >= 0
            ]
            if starts:
                notes = prev[min(starts):]
        with open(args.out, "w") as f:
            f.write(to_markdown(results, hdr) + notes)

    if args.compile_gate > 0:
        slow = [
            r for r in results
            if r["compile_s"] > args.compile_gate and not r.get("gate_exempt")
        ]
        if slow:
            for r in slow:
                print(
                    f"COMPILE GATE FAIL: {r['benchmark']} compiled in "
                    f"{r['compile_s']}s (> {args.compile_gate}s)",
                    file=sys.stderr,
                )
            sys.exit(1)
        print(
            f"# compile gate ok: all {len(results)} benchmarks compiled "
            f"under {args.compile_gate}s",
            file=sys.stderr,
        )


if __name__ == "__main__":
    main()
