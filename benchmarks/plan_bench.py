"""Planner benchmark: lazy optimized pipeline vs the eager op chain.

Runs the acceptance-shaped query — filter -> join -> groupby(sum) on the
join key — both ways on the virtual CPU mesh (or TPU when present):

- EAGER: distributed_join, then filter, then distributed_groupby — three
  shuffles, a materialized join, a groupby sort;
- LAZY:  the same query through the optimizer — filter below the shuffle,
  columns pruned before the exchange, the groupby shuffle eliminated, the
  join+groupby pair fused into join_sum_by_key_pushdown.

Asserts (via tracing.report) that the expected rules actually fired and
that the second lazy run hit the plan-fingerprint cache, then prints one
JSON line per measurement (warm timings, first-run compile excluded).

Usage: python benchmarks/plan_bench.py [--rows 1000000] [--world 8]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("CYLON_TPU_NO_X64", "1")

import __graft_entry__ as ge


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--world", type=int, default=8)
    ap.add_argument("--keyspace", type=int, default=50_000)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()

    devices = ge._force_cpu_mesh(args.world)
    import numpy as np

    import cylon_tpu as ct
    from cylon_tpu import col
    from cylon_tpu.plan import rules as plan_rules
    from cylon_tpu.plan.expr import filter_mask
    from cylon_tpu.utils import tracing

    ctx = ct.CylonContext.init_distributed(
        ct.TPUConfig(devices=devices[: args.world])
    )
    rng = np.random.default_rng(0)
    n = args.rows
    ta = ct.Table.from_numpy(
        ctx, ["k", "v", "extra"],
        [rng.integers(0, args.keyspace, n).astype(np.int32),
         rng.normal(size=n).astype(np.float32),
         rng.normal(size=n).astype(np.float32)],
    )
    tb = ct.Table.from_numpy(
        ctx, ["rk", "w"],
        [rng.integers(0, args.keyspace, n // 2).astype(np.int32),
         rng.normal(size=n // 2).astype(np.float32)],
    )

    def eager():
        j = ta.distributed_join(tb, left_on=["k"], right_on=["rk"])
        j = j.filter(filter_mask(
            col("w") > 0.0, {c: j.column(c) for c in j.column_names}))
        return j.distributed_groupby("k", {"v": "sum"})

    lf = (
        ta.lazy()
        .join(tb.lazy(), left_on="k", right_on="rk")
        .filter(col("w") > 0.0)
        .groupby("k", {"v": "sum"})
    )

    def timed(fn, reps):
        fn()  # warm (compile)
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn()
            out.row_count  # host-sync'd already; keep the result live
            ts.append(time.perf_counter() - t0)
        return min(ts)

    tracing.reset_trace()
    t_lazy = timed(lf.collect, args.reps)
    fired = {
        k.removeprefix("plan.rule."): int(v["count"])
        for k, v in tracing.report("plan.rule.").items()
    }
    for rule in (plan_rules.FILTER_PUSHDOWN, plan_rules.PROJECTION_PUSHDOWN,
                 plan_rules.SHUFFLE_ELIM, plan_rules.FUSED_JOIN_GROUPBY):
        assert fired.get(rule), f"expected rule {rule} to fire: {fired}"
    hits = tracing.get_count("plan.cache.hit")
    assert hits >= args.reps, "warm collects must hit the plan cache"
    t_eager = timed(eager, args.reps)

    print(json.dumps({
        "bench": "plan_filter_join_groupby_sum",
        "rows": n, "world": args.world, "keyspace": args.keyspace,
        "eager_s": round(t_eager, 4), "lazy_s": round(t_lazy, 4),
        "speedup": round(t_eager / t_lazy, 3),
        "rules_fired": fired,
        "plan_cache_hits": hits,
    }))


if __name__ == "__main__":
    main()
