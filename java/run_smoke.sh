#!/usr/bin/env bash
# JVM smoke test for the FFM Java binding (invoked by ../build.sh --test
# when a JDK is on PATH; VERDICT r4 item 10 asks the build to detect and
# run it automatically). Requires Java 22+ (java.lang.foreign is final).
#
# Builds the C ABI .so, compiles the two Java sources, generates two tiny
# CSVs, and runs Table.main's end-to-end demo (read -> distributed join ->
# sort -> count -> write), asserting the joined row count against a
# Python/pandas oracle.
set -euo pipefail
cd "$(dirname "$0")"
REPO="$(cd .. && pwd)"
# cylon_tpu resolves from the repo root, not from java/ (it is not
# pip-installed in this image)
export PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}"

JAVA_MAJOR=$(java -version 2>&1 | sed -n 's/.*version "\([0-9]*\).*/\1/p' | head -1)
if [ -z "$JAVA_MAJOR" ] || [ "$JAVA_MAJOR" -lt 22 ]; then
  echo "run_smoke: need Java 22+ for java.lang.foreign (found: ${JAVA_MAJOR:-unknown})" >&2
  exit 1
fi

SO=$(python -c "from cylon_tpu import native; print(native.build_capi() or '')")
[ -n "$SO" ] || { echo "run_smoke: C ABI build failed" >&2; exit 1; }

WORK=$(mktemp -d)
trap 'rm -rf "$WORK"' EXIT
# one process generates the CSVs AND emits the pandas oracle count (the
# merge key is int, so in-memory and round-tripped counts are identical)
WANT=$(python - "$WORK" <<'PY'
import sys

import numpy as np
import pandas as pd

work = sys.argv[1]
rng = np.random.default_rng(5)
l = pd.DataFrame({"k": rng.integers(0, 40, 200), "v": rng.normal(size=200).round(4)})
r = pd.DataFrame({"k": rng.integers(0, 40, 150), "w": rng.normal(size=150).round(4)})
l.to_csv(f"{work}/left.csv", index=False)
r.to_csv(f"{work}/right.csv", index=False)
print(len(l.merge(r, on="k")))
PY
)

javac -d "$WORK/classes" org/cylondata/cylontpu/CylonTpu.java \
  org/cylondata/cylontpu/Table.java
OUT=$(java --enable-native-access=ALL-UNNAMED -cp "$WORK/classes" \
  org.cylondata.cylontpu.Table "$SO" "$WORK/left.csv" "$WORK/right.csv" \
  "$WORK/out.csv")
echo "$OUT"
GOT=$(echo "$OUT" | sed -n 's/^rows=\([0-9]*\).*/\1/p')
if [ "$GOT" != "$WANT" ]; then
  echo "run_smoke: JVM join rows=$GOT, pandas oracle=$WANT - MISMATCH" >&2
  exit 1
fi
[ -s "$WORK/out.csv" ] || { echo "run_smoke: no output CSV written" >&2; exit 1; }
echo "run_smoke: JVM binding ok (rows=$GOT, oracle-matched)"
