/*
 * JVM-side runtime bridge for the cylon_tpu C ABI (native/capi.cpp).
 *
 * Reference analog: java/src/main/native/src/TwisterXContext.cpp +
 * Table.cpp — the JNI layer the reference hand-writes. Here the Java 22+
 * Foreign Function & Memory API (java.lang.foreign) binds the same C ABI
 * the standalone C client (native/examples/capi_client.c) uses, so no
 * hand-written JNI glue is needed at all.
 *
 * NOTE: this build image has no JVM, so this source is compiled and
 * exercised only where a JDK >= 22 exists:
 *
 *   javac java/org/cylondata/cylontpu/*.java
 *   java --enable-native-access=ALL-UNNAMED \
 *        org.cylondata.cylontpu.Table <capi.so> <l.csv> <r.csv> <out.csv>
 */
package org.cylondata.cylontpu;

import java.lang.foreign.Arena;
import java.lang.foreign.FunctionDescriptor;
import java.lang.foreign.Linker;
import java.lang.foreign.MemorySegment;
import java.lang.foreign.SymbolLookup;
import java.lang.invoke.MethodHandle;

import static java.lang.foreign.ValueLayout.ADDRESS;
import static java.lang.foreign.ValueLayout.JAVA_INT;
import static java.lang.foreign.ValueLayout.JAVA_LONG;

/** Process-wide binding to the cylon_tpu C ABI; one embedded interpreter. */
public final class CylonTpu {
  final MethodHandle lastError;
  final MethodHandle init;
  final MethodHandle readCsv;
  final MethodHandle join;
  final MethodHandle sort;
  final MethodHandle project;
  final MethodHandle rowCount;
  final MethodHandle columnCount;
  final MethodHandle writeCsv;
  final MethodHandle release;
  final MethodHandle shutdown;
  final MethodHandle select;
  final MethodHandle filterColumn;
  final MethodHandle mapColumn;
  final MethodHandle hashPartition;
  final MethodHandle merge;
  final MethodHandle print;
  final Linker linker;
  final Arena arena = Arena.ofShared();

  private static CylonTpu instance;
  private static String instancePath;

  /** Load the capi shared library and resolve every ct_api_* symbol.
   *  The embedded interpreter is process-wide, so only ONE library may ever
   *  be loaded; a different path on a later call is an error, and a failed
   *  init is retryable (the singleton is published only on success). */
  public static synchronized CylonTpu load(String capiSoPath) {
    if (instance != null) {
      if (!instance.samePath(capiSoPath)) {
        throw new IllegalStateException(
            "cylon_tpu already loaded from " + instancePath
                + "; cannot load " + capiSoPath);
      }
      return instance;
    }
    CylonTpu rt = new CylonTpu(capiSoPath);
    int rc;
    try {
      rc = (int) rt.init.invokeExact();
    } catch (Throwable t) {
      rt.arena.close(); // free the library mapping so a retry starts clean
      throw new RuntimeException("ct_api_init invocation failed", t);
    }
    if (rc != 0) {
      String err = rt.errorMessage();
      rt.arena.close();
      throw new RuntimeException("ct_api_init failed: " + err);
    }
    instance = rt;
    instancePath = capiSoPath;
    Runtime.getRuntime().addShutdownHook(new Thread(() -> {
      try {
        rt.shutdown.invokeExact();
      } catch (Throwable ignored) {
      }
    }));
    return instance;
  }

  private boolean samePath(String path) {
    return path != null && path.equals(instancePath);
  }

  private CylonTpu(String capiSoPath) {
    linker = Linker.nativeLinker();
    SymbolLookup lib = SymbolLookup.libraryLookup(capiSoPath, arena);
    lastError = handle(linker, lib, "ct_api_last_error",
        FunctionDescriptor.of(ADDRESS));
    init = handle(linker, lib, "ct_api_init", FunctionDescriptor.of(JAVA_INT));
    readCsv = handle(linker, lib, "ct_api_read_csv",
        FunctionDescriptor.of(JAVA_LONG, ADDRESS));
    join = handle(linker, lib, "ct_api_join",
        FunctionDescriptor.of(JAVA_LONG, JAVA_LONG, JAVA_LONG, ADDRESS, ADDRESS, JAVA_INT));
    sort = handle(linker, lib, "ct_api_sort",
        FunctionDescriptor.of(JAVA_LONG, JAVA_LONG, ADDRESS, JAVA_INT));
    project = handle(linker, lib, "ct_api_project",
        FunctionDescriptor.of(JAVA_LONG, JAVA_LONG, ADDRESS));
    rowCount = handle(linker, lib, "ct_api_row_count",
        FunctionDescriptor.of(JAVA_LONG, JAVA_LONG));
    columnCount = handle(linker, lib, "ct_api_column_count",
        FunctionDescriptor.of(JAVA_INT, JAVA_LONG));
    writeCsv = handle(linker, lib, "ct_api_write_csv",
        FunctionDescriptor.of(JAVA_INT, JAVA_LONG, ADDRESS));
    release = handle(linker, lib, "ct_api_release",
        FunctionDescriptor.ofVoid(JAVA_LONG));
    shutdown = handle(linker, lib, "ct_api_shutdown", FunctionDescriptor.ofVoid());
    // round-3 surface: callback-driven select/filter/map + partition/merge
    select = handle(linker, lib, "ct_api_select",
        FunctionDescriptor.of(JAVA_LONG, JAVA_LONG, ADDRESS, ADDRESS));
    filterColumn = handle(linker, lib, "ct_api_filter_column",
        FunctionDescriptor.of(JAVA_LONG, JAVA_LONG, JAVA_INT, ADDRESS, ADDRESS));
    mapColumn = handle(linker, lib, "ct_api_map_column",
        FunctionDescriptor.of(JAVA_LONG, JAVA_LONG, JAVA_INT, ADDRESS, ADDRESS));
    hashPartition = handle(linker, lib, "ct_api_hash_partition",
        FunctionDescriptor.of(JAVA_INT, JAVA_LONG, ADDRESS, JAVA_INT, ADDRESS));
    merge = handle(linker, lib, "ct_api_merge",
        FunctionDescriptor.of(JAVA_LONG, ADDRESS, JAVA_INT));
    print = handle(linker, lib, "ct_api_print",
        FunctionDescriptor.of(JAVA_INT, JAVA_LONG));
  }

  /** Upcall stub for ct_row_pred: int32 (*)(int64 row, const char* csv,
   *  void* user). The Java predicate sees (row index, the row as CSV). */
  MemorySegment rowPredStub(Arena a, java.util.function.BiPredicate<Long, String> pred) {
    try {
      MethodHandle target = java.lang.invoke.MethodHandles.lookup().bind(
          new Object() {
            @SuppressWarnings("unused")
            int call(long row, MemorySegment csv, MemorySegment user) {
              String s = csv.reinterpret(Long.MAX_VALUE).getString(0);
              return pred.test(row, s) ? 1 : 0;
            }
          },
          "call",
          java.lang.invoke.MethodType.methodType(
              int.class, long.class, MemorySegment.class, MemorySegment.class));
      return linker.upcallStub(target,
          FunctionDescriptor.of(JAVA_INT, JAVA_LONG, ADDRESS, ADDRESS), a);
    } catch (ReflectiveOperationException e) {
      throw new RuntimeException(e);
    }
  }

  /** Upcall stub for ct_val_pred: int32 (*)(const char* value, void* user). */
  MemorySegment valPredStub(Arena a, java.util.function.Predicate<String> pred) {
    try {
      MethodHandle target = java.lang.invoke.MethodHandles.lookup().bind(
          new Object() {
            @SuppressWarnings("unused")
            int call(MemorySegment value, MemorySegment user) {
              return pred.test(value.reinterpret(Long.MAX_VALUE).getString(0))
                  ? 1 : 0;
            }
          },
          "call",
          java.lang.invoke.MethodType.methodType(
              int.class, MemorySegment.class, MemorySegment.class));
      return linker.upcallStub(target,
          FunctionDescriptor.of(JAVA_INT, ADDRESS, ADDRESS), a);
    } catch (ReflectiveOperationException e) {
      throw new RuntimeException(e);
    }
  }

  /** Upcall stub for ct_val_map: int32 (*)(const char* in, char* out,
   *  int32 cap, void* user) — writes the mapped string, returns its length. */
  MemorySegment valMapStub(Arena a, java.util.function.UnaryOperator<String> fn) {
    try {
      MethodHandle target = java.lang.invoke.MethodHandles.lookup().bind(
          new Object() {
            @SuppressWarnings("unused")
            int call(MemorySegment in, MemorySegment out, int cap,
                MemorySegment user) {
              String s = fn.apply(in.reinterpret(Long.MAX_VALUE).getString(0));
              byte[] b = s.getBytes(java.nio.charset.StandardCharsets.UTF_8);
              if (b.length + 1 > cap) {
                return -1;
              }
              MemorySegment seg = out.reinterpret(cap);
              MemorySegment.copy(b, 0, seg, java.lang.foreign.ValueLayout.JAVA_BYTE, 0, b.length);
              seg.set(java.lang.foreign.ValueLayout.JAVA_BYTE, b.length, (byte) 0);
              return b.length;
            }
          },
          "call",
          java.lang.invoke.MethodType.methodType(int.class,
              MemorySegment.class, MemorySegment.class, int.class,
              MemorySegment.class));
      return linker.upcallStub(target,
          FunctionDescriptor.of(JAVA_INT, ADDRESS, ADDRESS, JAVA_INT, ADDRESS),
          a);
    } catch (ReflectiveOperationException e) {
      throw new RuntimeException(e);
    }
  }

  private static MethodHandle handle(Linker linker, SymbolLookup lib,
      String name, FunctionDescriptor desc) {
    MemorySegment sym = lib.find(name)
        .orElseThrow(() -> new UnsatisfiedLinkError("missing symbol " + name));
    return linker.downcallHandle(sym, desc);
  }

  /** The last ct_api error message (empty string when none). */
  public String errorMessage() {
    try {
      MemorySegment p = (MemorySegment) lastError.invokeExact();
      return p.reinterpret(Long.MAX_VALUE).getString(0);
    } catch (Throwable t) {
      return "(error message unavailable: " + t + ")";
    }
  }

  MemorySegment cstr(Arena a, String s) {
    return a.allocateFrom(s);
  }
}
