/*
 * JVM Table API over the cylon_tpu C ABI.
 *
 * Reference analog: java/src/main/java/org/cylondata/cylon/Table.java:63-238
 * (static fromCSV, join/distributedJoin, sort, select/project, rowCount,
 * columnCount, write). Same shape here, but every operation dispatches into
 * the TPU framework through capi.cpp's handle registry instead of JNI.
 *
 * See CylonTpu.java for how to compile/run (needs JDK >= 22; this build
 * image has none, so the class is validated by signature against
 * native/examples/capi_client.c, which exercises the identical ABI in C).
 */
package org.cylondata.cylontpu;

import java.lang.foreign.Arena;

/** An immutable handle to a cylon_tpu table living behind the C ABI. */
public final class Table implements AutoCloseable {
  private final CylonTpu rt;
  private final long handle;
  private boolean closed;

  private Table(CylonTpu rt, long handle) {
    this.rt = rt;
    this.handle = handle;
  }

  @FunctionalInterface
  private interface NativeCall<T> {
    T run(Arena a) throws Throwable;
  }

  /** One place for the call boilerplate: confined arena for C strings,
   *  native error message on failure, uniform exception wrapping. */
  private static <T> T call(CylonTpu rt, String op, NativeCall<T> body) {
    try (Arena a = Arena.ofConfined()) {
      return body.run(a);
    } catch (RuntimeException e) {
      throw e;
    } catch (Throwable t) {
      throw new RuntimeException(
          op + " failed: " + rt.errorMessage(), t);
    }
  }

  private static Table wrap(CylonTpu rt, long h, String op) {
    if (h == 0) {
      throw new RuntimeException(op + " failed: " + rt.errorMessage());
    }
    return new Table(rt, h);
  }

  /** Reference Table.java fromCSV(ctx, path) :63. */
  public static Table fromCSV(CylonTpu rt, String path) {
    return call(rt, "read_csv", a ->
        wrap(rt, (long) rt.readCsv.invokeExact(rt.cstr(a, path)),
            "read_csv(" + path + ")"));
  }

  /** Local equi-join; how in {inner,left,right,outer}. Reference :126. */
  public Table join(Table right, String on, String how) {
    return joinImpl(right, on, how, 0);
  }

  /** Distributed join over the device mesh. Reference distributedJoin :150. */
  public Table distributedJoin(Table right, String on, String how) {
    return joinImpl(right, on, how, 1);
  }

  private Table joinImpl(Table right, String on, String how, int dist) {
    return call(rt, "join", a ->
        wrap(rt, (long) rt.join.invokeExact(
            handle, right.handle, rt.cstr(a, on), rt.cstr(a, how), dist),
            "join"));
  }

  /** Sort by one column (ascending). Reference sort :190. */
  public Table sort(String column, boolean distributed) {
    return call(rt, "sort", a ->
        wrap(rt, (long) rt.sort.invokeExact(
            handle, rt.cstr(a, column), distributed ? 1 : 0), "sort"));
  }

  /** Keep only the named columns (comma-separated). Reference select :219. */
  public Table project(String columnsCsv) {
    return call(rt, "project", a ->
        wrap(rt, (long) rt.project.invokeExact(
            handle, rt.cstr(a, columnsCsv)), "project"));
  }

  /** Global live row count. Reference rowCount :200. */
  public long rowCount() {
    return call(rt, "row_count", a -> {
      long n = (long) rt.rowCount.invokeExact(handle);
      if (n < 0) {
        throw new RuntimeException("row_count failed: " + rt.errorMessage());
      }
      return n;
    });
  }

  /** Column count. Reference columnCount :205. */
  public int columnCount() {
    return call(rt, "column_count", a -> {
      int n = (int) rt.columnCount.invokeExact(handle);
      if (n < 0) {
        throw new RuntimeException("column_count failed: " + rt.errorMessage());
      }
      return n;
    });
  }

  /**
   * Row-UDF select (reference select(Selector) :226-238 — the one callback
   * method the reference actually implements through JNI). The predicate
   * receives (row index, the row rendered as a CSV line).
   */
  public Table select(java.util.function.BiPredicate<Long, String> pred) {
    return call(rt, "select", a ->
        wrap(rt, (long) rt.select.invokeExact(
            handle, rt.rowPredStub(a, pred),
            java.lang.foreign.MemorySegment.NULL), "select"));
  }

  /**
   * Single-column value filter (reference filter(col, Filter) :214 — which
   * throws unSupportedException in the reference; implemented for real
   * here). Values arrive as their string rendering.
   */
  public Table filter(int colIndex, java.util.function.Predicate<String> pred) {
    return call(rt, "filter", a ->
        wrap(rt, (long) rt.filterColumn.invokeExact(
            handle, colIndex, rt.valPredStub(a, pred),
            java.lang.foreign.MemorySegment.NULL), "filter"));
  }

  /**
   * Per-element column map (reference mapColumn :156 — unSupportedException
   * there; real here). Returns a new 1-column table; the result dtype is
   * re-inferred from the mapped strings.
   */
  public Table mapColumn(int colIndex, java.util.function.UnaryOperator<String> fn) {
    return call(rt, "mapColumn", a ->
        wrap(rt, (long) rt.mapColumn.invokeExact(
            handle, colIndex, rt.valMapStub(a, fn),
            java.lang.foreign.MemorySegment.NULL), "mapColumn"));
  }

  /**
   * Hash partition into k tables (reference hashPartition :166 —
   * unSupportedException there; the C++ core HashPartition is the analog).
   */
  public java.util.List<Table> hashPartition(String columnsCsv, int k) {
    return call(rt, "hashPartition", a -> {
      java.lang.foreign.MemorySegment out = a.allocate(
          java.lang.foreign.ValueLayout.JAVA_LONG, k);
      int rc = (int) rt.hashPartition.invokeExact(
          handle, rt.cstr(a, columnsCsv), k, out);
      if (rc != 0) {
        throw new RuntimeException("hashPartition failed: " + rt.errorMessage());
      }
      java.util.List<Table> parts = new java.util.ArrayList<>(k);
      for (int p = 0; p < k; p++) {
        parts.add(new Table(rt,
            out.getAtIndex(java.lang.foreign.ValueLayout.JAVA_LONG, p)));
      }
      return parts;
    });
  }

  /** Merge same-schema tables (reference static merge :187). */
  public static Table merge(CylonTpu rt, Table... tables) {
    return call(rt, "merge", a -> {
      java.lang.foreign.MemorySegment hs = a.allocate(
          java.lang.foreign.ValueLayout.JAVA_LONG, tables.length);
      for (int i = 0; i < tables.length; i++) {
        hs.setAtIndex(java.lang.foreign.ValueLayout.JAVA_LONG, i,
            tables[i].handle);
      }
      return wrap(rt, (long) rt.merge.invokeExact(hs, tables.length), "merge");
    });
  }

  /** Print the table head to stdout (reference print -> JNI print). */
  public void print() {
    call(rt, "print", a -> {
      int rc = (int) rt.print.invokeExact(handle);
      if (rc != 0) {
        throw new RuntimeException("print failed: " + rt.errorMessage());
      }
      return null;
    });
  }

  /** Write the table to CSV (gathered on the host edge). Reference :233. */
  public void writeCSV(String path) {
    call(rt, "write_csv", a -> {
      int rc = (int) rt.writeCsv.invokeExact(handle, rt.cstr(a, path));
      if (rc != 0) {
        throw new RuntimeException("write_csv failed: " + rt.errorMessage());
      }
      return null;
    });
  }

  /** Release the native handle (idempotent). */
  @Override
  public void close() {
    if (!closed) {
      closed = true;
      try {
        rt.release.invokeExact(handle);
      } catch (Throwable ignored) {
      }
    }
  }

  /**
   * End-to-end demo mirroring native/examples/capi_client.c: read two CSVs,
   * distributed-join on "k", sort, project, count, write.
   */
  public static void main(String[] args) {
    if (args.length != 4) {
      System.err.println(
          "usage: Table <capi.so> <left.csv> <right.csv> <out.csv>");
      System.exit(2);
    }
    CylonTpu rt = CylonTpu.load(args[0]);
    try (Table left = Table.fromCSV(rt, args[1]);
         Table right = Table.fromCSV(rt, args[2]);
         Table joined = left.distributedJoin(right, "k", "inner");
         Table sorted = joined.sort("k", true)) {
      System.out.println(
          "rows=" + sorted.rowCount() + " cols=" + sorted.columnCount());
      sorted.writeCSV(args[3]);
    }
  }
}
