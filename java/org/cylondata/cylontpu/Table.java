/*
 * JVM Table API over the cylon_tpu C ABI.
 *
 * Reference analog: java/src/main/java/org/cylondata/cylon/Table.java:63-238
 * (static fromCSV, join/distributedJoin, sort, select/project, rowCount,
 * columnCount, write). Same shape here, but every operation dispatches into
 * the TPU framework through capi.cpp's handle registry instead of JNI.
 *
 * See CylonTpu.java for how to compile/run (needs JDK >= 22; this build
 * image has none, so the class is validated by signature against
 * native/examples/capi_client.c, which exercises the identical ABI in C).
 */
package org.cylondata.cylontpu;

import java.lang.foreign.Arena;

/** An immutable handle to a cylon_tpu table living behind the C ABI. */
public final class Table implements AutoCloseable {
  private final CylonTpu rt;
  private final long handle;
  private boolean closed;

  private Table(CylonTpu rt, long handle) {
    this.rt = rt;
    this.handle = handle;
  }

  @FunctionalInterface
  private interface NativeCall<T> {
    T run(Arena a) throws Throwable;
  }

  /** One place for the call boilerplate: confined arena for C strings,
   *  native error message on failure, uniform exception wrapping. */
  private static <T> T call(CylonTpu rt, String op, NativeCall<T> body) {
    try (Arena a = Arena.ofConfined()) {
      return body.run(a);
    } catch (RuntimeException e) {
      throw e;
    } catch (Throwable t) {
      throw new RuntimeException(
          op + " failed: " + rt.errorMessage(), t);
    }
  }

  private static Table wrap(CylonTpu rt, long h, String op) {
    if (h == 0) {
      throw new RuntimeException(op + " failed: " + rt.errorMessage());
    }
    return new Table(rt, h);
  }

  /** Reference Table.java fromCSV(ctx, path) :63. */
  public static Table fromCSV(CylonTpu rt, String path) {
    return call(rt, "read_csv", a ->
        wrap(rt, (long) rt.readCsv.invokeExact(rt.cstr(a, path)),
            "read_csv(" + path + ")"));
  }

  /** Local equi-join; how in {inner,left,right,outer}. Reference :126. */
  public Table join(Table right, String on, String how) {
    return joinImpl(right, on, how, 0);
  }

  /** Distributed join over the device mesh. Reference distributedJoin :150. */
  public Table distributedJoin(Table right, String on, String how) {
    return joinImpl(right, on, how, 1);
  }

  private Table joinImpl(Table right, String on, String how, int dist) {
    return call(rt, "join", a ->
        wrap(rt, (long) rt.join.invokeExact(
            handle, right.handle, rt.cstr(a, on), rt.cstr(a, how), dist),
            "join"));
  }

  /** Sort by one column (ascending). Reference sort :190. */
  public Table sort(String column, boolean distributed) {
    return call(rt, "sort", a ->
        wrap(rt, (long) rt.sort.invokeExact(
            handle, rt.cstr(a, column), distributed ? 1 : 0), "sort"));
  }

  /** Keep only the named columns (comma-separated). Reference select :219. */
  public Table project(String columnsCsv) {
    return call(rt, "project", a ->
        wrap(rt, (long) rt.project.invokeExact(
            handle, rt.cstr(a, columnsCsv)), "project"));
  }

  /** Global live row count. Reference rowCount :200. */
  public long rowCount() {
    return call(rt, "row_count", a -> {
      long n = (long) rt.rowCount.invokeExact(handle);
      if (n < 0) {
        throw new RuntimeException("row_count failed: " + rt.errorMessage());
      }
      return n;
    });
  }

  /** Column count. Reference columnCount :205. */
  public int columnCount() {
    return call(rt, "column_count", a -> {
      int n = (int) rt.columnCount.invokeExact(handle);
      if (n < 0) {
        throw new RuntimeException("column_count failed: " + rt.errorMessage());
      }
      return n;
    });
  }

  /** Write the table to CSV (gathered on the host edge). Reference :233. */
  public void writeCSV(String path) {
    call(rt, "write_csv", a -> {
      int rc = (int) rt.writeCsv.invokeExact(handle, rt.cstr(a, path));
      if (rc != 0) {
        throw new RuntimeException("write_csv failed: " + rt.errorMessage());
      }
      return null;
    });
  }

  /** Release the native handle (idempotent). */
  @Override
  public void close() {
    if (!closed) {
      closed = true;
      try {
        rt.release.invokeExact(handle);
      } catch (Throwable ignored) {
      }
    }
  }

  /**
   * End-to-end demo mirroring native/examples/capi_client.c: read two CSVs,
   * distributed-join on "k", sort, project, count, write.
   */
  public static void main(String[] args) {
    if (args.length != 4) {
      System.err.println(
          "usage: Table <capi.so> <left.csv> <right.csv> <out.csv>");
      System.exit(2);
    }
    CylonTpu rt = CylonTpu.load(args[0]);
    try (Table left = Table.fromCSV(rt, args[1]);
         Table right = Table.fromCSV(rt, args[2]);
         Table joined = left.distributedJoin(right, "k", "inner");
         Table sorted = joined.sort("k", true)) {
      System.out.println(
          "rows=" + sorted.rowCount() + " cols=" + sorted.columnCount());
      sorted.writeCSV(args[3]);
    }
  }
}
