"""Native runtime: arena pool, murmur3, C-ABI binding layer.

Reference analogs: ctx/memory_pool.hpp (pool), util/murmur3.cpp (hash),
java/ JNI bindings (capi.cpp).
"""
import ctypes
import os

import numpy as np
import pytest

from cylon_tpu import native


pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def test_pool_alloc_reset_stats():
    pool = native.MemoryPool(block_bytes=4096)
    a = pool.alloc_array((100,), np.int64)
    a[:] = np.arange(100)
    assert a.sum() == 4950
    b = pool.alloc_array((8, 4), np.float64)
    b[:] = 1.5
    assert pool.alloc_count == 2
    assert pool.bytes_in_use >= 100 * 8 + 8 * 4 * 8
    peak1 = pool.bytes_peak
    pool.reset()
    assert pool.bytes_in_use == 0
    assert pool.bytes_peak == peak1
    # reuse after reset: same arena, no growth for same-size allocs
    reserved = pool.bytes_reserved
    c = pool.alloc_array((100,), np.int64)
    c[:] = 7
    assert pool.bytes_reserved == reserved
    pool.close()


def test_pool_oversized_block():
    pool = native.MemoryPool(block_bytes=256)
    big = pool.alloc_array((10000,), np.int64)  # >> block size
    big[:] = 3
    small = pool.alloc_array((4,), np.int32)
    small[:] = 9
    assert big.sum() == 30000 and small.sum() == 36
    pool.close()


def test_murmur3_known_vectors():
    """MurmurHash3_x86_32 reference vectors (public test vectors)."""
    lib = native.get_lib()
    assert lib.ct_murmur3_32(b"", 0, 0) == 0
    assert lib.ct_murmur3_32(b"", 0, 1) == 0x514E28B7
    assert lib.ct_murmur3_32(b"abc", 3, 0) == 0xB3DD93FA
    assert lib.ct_murmur3_32(b"Hello, world!", 13, 1234) == 0xFAF6CDB3


def test_murmur3_batch_matches_single():
    lib = native.get_lib()
    vals = np.array(["ant", "bee", "", "a much longer string value"])
    out = native.murmur3_strings(vals)
    for s, h in zip(vals, out):
        b = str(s).encode()
        assert lib.ct_murmur3_32(b, len(b), 0) == h


def test_capi_roundtrip(tmp_path):
    """Drive the framework through the C ABI the way a JVM/FFI user would
    (reference Table.java fromCSV/join/rowCount)."""
    so = native.build_capi()
    if so is None:
        pytest.skip("capi build failed (no libpython?)")
    lib = ctypes.CDLL(so)
    lib.ct_api_init.restype = ctypes.c_int
    lib.ct_api_read_csv.restype = ctypes.c_int64
    lib.ct_api_read_csv.argtypes = [ctypes.c_char_p]
    lib.ct_api_join.restype = ctypes.c_int64
    lib.ct_api_join.argtypes = [
        ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.ct_api_row_count.restype = ctypes.c_int64
    lib.ct_api_row_count.argtypes = [ctypes.c_int64]
    lib.ct_api_column_count.restype = ctypes.c_int32
    lib.ct_api_column_count.argtypes = [ctypes.c_int64]
    lib.ct_api_write_csv.restype = ctypes.c_int
    lib.ct_api_write_csv.argtypes = [ctypes.c_int64, ctypes.c_char_p]
    lib.ct_api_last_error.restype = ctypes.c_char_p
    lib.ct_api_release.argtypes = [ctypes.c_int64]

    import pandas as pd

    l = pd.DataFrame({"k": [1, 2, 3, 2], "x": [1.0, 2.0, 3.0, 4.0]})
    r = pd.DataFrame({"k": [2, 3, 4], "y": [10.0, 20.0, 30.0]})
    lp, rp = str(tmp_path / "l.csv"), str(tmp_path / "r.csv")
    l.to_csv(lp, index=False)
    r.to_csv(rp, index=False)

    assert lib.ct_api_init() == 0, lib.ct_api_last_error().decode()
    hl = lib.ct_api_read_csv(lp.encode())
    hr = lib.ct_api_read_csv(rp.encode())
    assert hl and hr, lib.ct_api_last_error().decode()
    hj = lib.ct_api_join(hl, hr, b"k", b"inner", 0)
    assert hj, lib.ct_api_last_error().decode()
    assert lib.ct_api_row_count(hj) == len(l.merge(r, on="k"))
    assert lib.ct_api_column_count(hj) == 4
    out = str(tmp_path / "out.csv")
    assert lib.ct_api_write_csv(hj, out.encode()) == 0
    assert os.path.exists(out)
    # bad input surfaces an error, not a crash
    assert lib.ct_api_join(hj, 999999, b"k", b"inner", 0) == 0
    assert b"handle" in lib.ct_api_last_error()
    for h in (hl, hr, hj):
        lib.ct_api_release(h)


def test_capi_table_from_raw_buffers(tmp_path):
    """Raw C-buffer ingest through the C ABI (reference arrow_builder.cpp
    raw-address Build used by JNI)."""
    import ctypes

    so = native.build_capi()
    if so is None:
        pytest.skip("capi build failed")
    lib = ctypes.CDLL(so)
    lib.ct_api_init.restype = ctypes.c_int
    lib.ct_api_last_error.restype = ctypes.c_char_p
    lib.ct_api_table_from_columns.restype = ctypes.c_int64
    lib.ct_api_table_from_columns.argtypes = [
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.c_int64,
    ]
    lib.ct_api_row_count.restype = ctypes.c_int64
    lib.ct_api_row_count.argtypes = [ctypes.c_int64]
    lib.ct_api_column_count.restype = ctypes.c_int32
    lib.ct_api_column_count.argtypes = [ctypes.c_int64]
    lib.ct_api_write_csv.restype = ctypes.c_int
    lib.ct_api_write_csv.argtypes = [ctypes.c_int64, ctypes.c_char_p]
    lib.ct_api_release.argtypes = [ctypes.c_int64]

    assert lib.ct_api_init() == 0, lib.ct_api_last_error().decode()
    n = 1000
    a = np.arange(n, dtype=np.int64)
    b = np.sqrt(np.arange(n, dtype=np.float64))
    c = (np.arange(n) % 3 == 0)
    names = (ctypes.c_char_p * 3)(b"a", b"b", b"flag")
    types = (ctypes.c_int32 * 3)(0, 1, 2)
    bufs = (ctypes.c_void_p * 3)(
        a.ctypes.data, b.ctypes.data, c.ctypes.data
    )
    h = lib.ct_api_table_from_columns(3, names, types, bufs, n)
    assert h, lib.ct_api_last_error().decode()
    assert lib.ct_api_row_count(h) == n
    assert lib.ct_api_column_count(h) == 3
    out = str(tmp_path / "buf.csv")
    assert lib.ct_api_write_csv(h, out.encode()) == 0
    import pandas as pd

    got = pd.read_csv(out)
    assert got["a"].tolist() == a.tolist()
    assert np.allclose(got["b"].to_numpy(), b)
    lib.ct_api_release(h)
    # bad type tag errors cleanly
    types_bad = (ctypes.c_int32 * 3)(0, 9, 2)
    assert lib.ct_api_table_from_columns(3, names, types_bad, bufs, n) == 0
