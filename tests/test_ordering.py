"""Order-property propagation tests (ISSUE 3).

Three layers:
  1. descriptor correctness — which ops establish, carry, and destroy the
     ordering descriptor (incl. survival/invalidation across the K-round
     chunked shuffle);
  2. differential — every sorted-input fast path (groupby run-detect, sort
     no-op/suffix, unique run-detect, single-column set-op probe, key-order
     join emit, presorted-right probe) against the generic path with the
     consumer gates disabled (CYLON_TPU_NO_ORDERING=1), on randomized
     tables (the fuzz oracle pattern);
  3. the pinned q3 acceptance — join->groupby-SUM through the key-order
     emit must run >= 30% fewer traced sort-pass bytes than the eager
     unordered path, with identical output, and ``.explain()`` must show
     the elided groupby lexsort.
"""
import os
import sys

import numpy as np
import pandas as pd
import pandas.testing as pdt
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import cylon_tpu as ct
from cylon_tpu import Ordering
from cylon_tpu import ordering as ordmod
from cylon_tpu.plan import rules as plan_rules
from cylon_tpu.utils.tracing import get_count, reset_trace


@pytest.fixture(scope="module")
def ctx1(devices):
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:1]))


@pytest.fixture(scope="module")
def ctx4(devices):
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:4]))


def _tables(ctx, rng, n=2000, keyspace=None, fanout_safe=True):
    keyspace = keyspace or (n if fanout_safe else 50)
    lt = ct.Table.from_pydict(ctx, {
        "k": rng.integers(0, keyspace, n).astype(np.int32),
        "v": rng.normal(size=n).astype(np.float32),
    })
    rt = ct.Table.from_pydict(ctx, {
        "k": rng.integers(0, keyspace, n).astype(np.int32),
        "w": rng.normal(size=n).astype(np.float32),
    })
    return lt, rt


def _gates_off():
    return ordmod.disabled()  # the ONE env toggle (cylon_tpu/ordering.py)


# ----------------------------------------------------------------------
# 1. descriptor lifecycle
# ----------------------------------------------------------------------
def test_sort_establishes_descriptor(ctx1):
    rng = np.random.default_rng(0)
    lt, _ = _tables(ctx1, rng, n=500)
    assert lt.ordering is None
    s = lt.sort(["k", "v"], ascending=[True, False])
    o = s.ordering
    assert o is not None
    assert o.keys == ("k", "v") and o.ascending == (True, False)
    assert o.scope == "shard" and o.lexsort_exact
    # descending second key: not canonical
    assert not o.canonical
    s2 = lt.sort("k")
    assert s2.ordering.canonical  # mask-free ascending


def test_descriptor_validation():
    with pytest.raises(ValueError):
        ordmod.validate(Ordering(keys=(), ascending=()), ["a"])
    with pytest.raises(ValueError):
        ordmod.validate(
            Ordering(keys=("nope",), ascending=(True,)), ["a"]
        )
    with pytest.raises(ValueError):
        ordmod.validate(
            Ordering(keys=("a",), ascending=(True, False)), ["a"]
        )
    with pytest.raises(ValueError):  # canonical demands ascending
        ordmod.validate(
            Ordering(keys=("a",), ascending=(False,), canonical=True), ["a"]
        )


def test_with_ordering_rejects_unknown_key(ctx1):
    rng = np.random.default_rng(1)
    lt, _ = _tables(ctx1, rng, n=100)
    with pytest.raises(ValueError):
        lt.with_ordering(Ordering(keys=("zz",), ascending=(True,)))


def test_carry_and_truncate(ctx1):
    rng = np.random.default_rng(2)
    lt, _ = _tables(ctx1, rng, n=500)
    s = lt.sort(["k", "v"])
    # filter / project / rename / drop / set_index carry or truncate
    assert s.filter(s.column("v").data > 0).ordering.keys == ("k", "v")
    assert s.project(["k"]).ordering.keys == ("k",)
    assert s.project(["v"]).ordering is None  # 'v' is not a key PREFIX
    assert s.rename({"k": "key"}).ordering.keys == ("key", "v")
    assert s.drop(["v"]).ordering.keys == ("k",)
    assert s.set_index("k").ordering is not None
    # unique keeps a subset of rows in order
    assert s.unique(["k"]).ordering.keys == ("k", "v")


def test_groupby_output_is_key_ordered(ctx1):
    rng = np.random.default_rng(3)
    lt, _ = _tables(ctx1, rng, n=800, keyspace=60)
    g = lt.groupby("k", {"v": "sum"})
    o = g.ordering
    assert o is not None and o.keys == ("k",) and o.canonical
    kv = g.to_pandas()["k"].to_numpy()
    assert (np.diff(kv) >= 0).all()


def test_shuffle_invalidates_across_chunked_rounds(ctx4):
    """Survival check at K>1: a multi-round chunked shuffle must DROP the
    descriptor (rounds land source-major and interleave key ranges)."""
    from cylon_tpu.parallel import shuffle as sh
    from cylon_tpu.utils.tracing import report

    rng = np.random.default_rng(4)
    lt, _ = _tables(ctx4, rng, n=4000)
    s = lt.sort("k")
    assert s.ordering is not None
    reset_trace()
    # tiny budget forces K > 1 rounds
    shuffled = s.shuffle(["k"], byte_budget=2048)
    rounds = int(report("shuffle.")["shuffle.rounds"]["rows"])
    assert rounds > 1, "budget did not force a multi-round shuffle"
    assert shuffled.ordering is None
    # and at K == 1 too
    assert s.shuffle(["k"], byte_budget=1 << 40).ordering is None
    assert sh.ordering_after_shuffle("hash") is None
    assert sh.ordering_after_shuffle("range") is None
    with pytest.raises(ValueError):
        sh.ordering_after_shuffle("bogus")


def test_distributed_sort_sets_global_scope_and_elides(ctx4):
    rng = np.random.default_rng(5)
    lt, _ = _tables(ctx4, rng, n=3000)
    s = lt.distributed_sort("k")
    assert s.ordering is not None and s.ordering.scope == "global"
    reset_trace()
    s2 = s.distributed_sort("k")
    assert get_count("ordering.dist_sort_elided") == 1
    assert s2.ordering == s.ordering
    pdt.assert_frame_equal(s2.to_pandas(), s.to_pandas())


def test_inplace_mutation_drops_descriptor(ctx1):
    rng = np.random.default_rng(6)
    lt, _ = _tables(ctx1, rng, n=200)
    s = lt.sort("k")
    assert s.ordering is not None
    s["v2"] = np.arange(s.row_count, dtype=np.float32)
    assert s.ordering is None


def test_plan_sees_mutation_not_stale_scan_capture(ctx1):
    """A plan built over a sorted table, collected AFTER an in-place
    mutation cleared the descriptor, must NOT elide its Sort off the stale
    plan-build-time claim."""
    rng = np.random.default_rng(60)
    lt, _ = _tables(ctx1, rng, n=400)
    s = lt.sort("k")
    lf = s.lazy().sort("k")
    assert plan_rules.ORDER_REUSE in lf.explain()  # elidable right now
    # in-place mutation scrambles k and clears the descriptor
    s["k"] = rng.permutation(s.to_pandas()["k"].to_numpy())
    assert plan_rules.ORDER_REUSE not in lf.explain()
    out = lf.collect().to_pandas()["k"].to_numpy()
    assert (np.diff(out) >= 0).all(), "stale Scan ordering elided a needed sort"


# ----------------------------------------------------------------------
# 2. differential fast paths (gates on vs off)
# ----------------------------------------------------------------------
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_groupby_run_detect_differential(ctx1, seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(100, 2000))
    lt, _ = _tables(ctx1, rng, n=n, keyspace=int(rng.integers(2, 80)))
    s = lt.sort("k")
    reset_trace()
    got = s.groupby("k", {"v": ["sum", "count", "mean"]})
    assert get_count("ordering.groupby_run_detect") == 1
    with _gates_off():
        want = s.groupby("k", {"v": ["sum", "count", "mean"]})
    pdt.assert_frame_equal(got.to_pandas(), want.to_pandas())


def test_sort_noop_and_suffix_differential(ctx1):
    rng = np.random.default_rng(7)
    lt, _ = _tables(ctx1, rng, n=1500, keyspace=40)
    s = lt.sort("k")
    reset_trace()
    e = s.sort("k")
    assert get_count("ordering.sort_elided") == 1
    pdt.assert_frame_equal(e.to_pandas(), s.to_pandas())
    # the elided result is a fresh handle: mutating it must not write
    # through to the source table
    e["z"] = np.zeros(e.row_count, np.float32)
    assert "z" not in s.column_names and s.ordering is not None
    got = s.sort(["k", "v"])
    assert get_count("ordering.sort_suffix") == 1
    with _gates_off():
        want = s.sort(["k", "v"])
    pdt.assert_frame_equal(got.to_pandas(), want.to_pandas())
    # and against a from-scratch full sort of the source table
    pdt.assert_frame_equal(got.to_pandas(), lt.sort(["k", "v"]).to_pandas())
    # direction mismatch on the prefix must NOT elide
    reset_trace()
    d = s.sort("k", ascending=False)
    assert get_count("ordering.sort_elided") == 0
    assert (np.diff(d.to_pandas()["k"].to_numpy()) <= 0).all()


@pytest.mark.parametrize("keep", ["first", "last"])
def test_unique_run_detect_differential(ctx1, keep):
    rng = np.random.default_rng(8)
    lt, _ = _tables(ctx1, rng, n=1200, keyspace=30)
    s = lt.sort("k")
    reset_trace()
    got = s.unique(["k"], keep=keep)
    assert get_count("ordering.unique_run_detect") == 1
    with _gates_off():
        want = s.unique(["k"], keep=keep)
    pdt.assert_frame_equal(got.to_pandas(), want.to_pandas())


@pytest.mark.parametrize("op", ["union", "subtract", "intersect"])
def test_setop_sorted_probe_differential(ctx1, op):
    rng = np.random.default_rng(9)
    lt, rt = _tables(ctx1, rng, n=900, keyspace=70)
    lk, rk = lt.project(["k"]).sort("k"), rt.project(["k"]).sort("k")
    reset_trace()
    got = getattr(lk, op)(rk)
    assert get_count("ordering.setop_sorted_probe") == 1
    with _gates_off():
        want = getattr(lk, op)(rk)
    pdt.assert_frame_equal(got.to_pandas(), want.to_pandas())


def test_join_presorted_right_differential(ctx1):
    rng = np.random.default_rng(10)
    lt, rt = _tables(ctx1, rng, n=1500)
    rs = rt.sort("k")
    reset_trace()
    got = lt.join(rs, on="k", how="inner")
    assert get_count("ordering.join_presorted_probe") == 1
    with _gates_off():
        want = lt.join(rs, on="k", how="inner")
    pdt.assert_frame_equal(got.to_pandas(), want.to_pandas())


@pytest.mark.parametrize("how", ["inner", "left"])
def test_join_key_order_emit_differential(ctx1, how):
    rng = np.random.default_rng(11)
    lt, rt = _tables(ctx1, rng, n=1500)
    got = lt.join(rt, on="k", how=how, emit_order="key")
    assert got.ordering is not None and got.ordering.keys == ("k_x",)
    kv = got.to_pandas()["k_x"].to_numpy()
    assert (np.diff(kv) >= 0).all(), "key-order emit not key-sorted"
    plain = lt.join(rt, on="k", how=how)
    cols = ["k_x", "v", "w"]
    pdt.assert_frame_equal(
        got.to_pandas().sort_values(cols).reset_index(drop=True),
        plain.to_pandas().sort_values(cols).reset_index(drop=True),
    )


def test_join_key_order_overflow_falls_back(ctx1):
    """Fanout past the speculative cap: the key-order request must degrade
    to a correct left-order join with NO descriptor, never a wrong claim."""
    rng = np.random.default_rng(12)
    n = 3000
    lt, rt = _tables(ctx1, rng, n=n, keyspace=None, fanout_safe=False)
    got = lt.join(rt, on="k", how="inner", emit_order="key")
    assert got.ordering is None  # overflow -> two-phase left-order path
    plain = lt.join(rt, on="k", how="inner")
    cols = ["k_x", "v", "w"]
    pdt.assert_frame_equal(
        got.to_pandas().sort_values(cols).reset_index(drop=True),
        plain.to_pandas().sort_values(cols).reset_index(drop=True),
    )


def test_join_key_order_rejects_right_outer(ctx1):
    rng = np.random.default_rng(13)
    lt, rt = _tables(ctx1, rng, n=100)
    for how in ("right", "outer"):
        with pytest.raises(ValueError):
            lt.join(rt, on="k", how=how, emit_order="key")
    with pytest.raises(ValueError):
        lt.distributed_join(rt, on="k", mode="fused", emit_order="key")


def test_null_keys_key_order_join_groupby(ctx1):
    """Null join keys through the key-order emit + groupby run-detect: the
    canonical descriptor must keep null==null adjacency intact."""
    rng = np.random.default_rng(14)
    n = 600
    k = rng.integers(0, 40, n).astype(np.float64)
    k[rng.random(n) < 0.2] = np.nan
    ldf = pd.DataFrame({"k": k, "v": rng.normal(size=n).astype(np.float32)})
    rdf = pd.DataFrame({
        "k": rng.permutation(np.arange(40).astype(np.float64)),
        "w": rng.normal(size=40).astype(np.float32),
    })
    lt = ct.Table.from_pandas(ctx1, ldf)
    rt = ct.Table.from_pandas(ctx1, rdf)
    j = lt.join(rt, on="k", how="left", emit_order="key")
    g = j.groupby("k_x", {"v": "sum"})
    with _gates_off():
        want = lt.join(rt, on="k", how="left").groupby("k_x", {"v": "sum"})
    sort_cols = ["k_x", "v_sum"]
    pdt.assert_frame_equal(
        g.to_pandas().sort_values(sort_cols).reset_index(drop=True),
        want.to_pandas().sort_values(sort_cols).reset_index(drop=True),
    )


# ----------------------------------------------------------------------
# satellite: take() uniform-shard short-circuit
# ----------------------------------------------------------------------
def test_take_uniform_short_circuit_matches_general(ctx4):
    rng = np.random.default_rng(15)
    # 4 shards x 250 rows: perfectly uniform -> divmod path
    lt, _ = _tables(ctx4, rng, n=1000)
    assert lt.row_counts.max() == lt.row_counts.min()
    idx = rng.integers(0, 1000, 300)
    got = lt.take(idx).to_pandas()
    host = lt.to_pandas()
    pdt.assert_frame_equal(got, host.iloc[idx].reset_index(drop=True))
    # negative indices still work through the short circuit
    got2 = lt.take(np.array([-1, 0, -1000])).to_pandas()
    pdt.assert_frame_equal(
        got2, host.iloc[[999, 0, 0]].reset_index(drop=True)
    )
    # non-uniform shards (filter skews counts) take the searchsorted path
    flt = lt.filter(lt.column("v").data > 0.3)
    if flt.row_counts.max() != flt.row_counts.min():
        m = flt.row_count
        idx2 = rng.integers(0, m, min(m, 100))
        pdt.assert_frame_equal(
            flt.take(idx2).to_pandas(),
            flt.to_pandas().iloc[idx2].reset_index(drop=True),
        )


# ----------------------------------------------------------------------
# 3. the pinned q3 acceptance + explain
# ----------------------------------------------------------------------
def _sort_totals(op):
    from benchmarks.roofline import Report, analyze
    from cylon_tpu import engine

    op()  # warm
    engine.record_kernels(True)
    try:
        op()
    finally:
        kernels = engine.recorded_kernels()
        engine.record_kernels(False)
    total = Report()
    for fn, args in kernels:
        rep = analyze(fn, *args)
        total.sort_count += rep.sort_count
        total.sort_pass_bytes += rep.sort_pass_bytes
    return total


@pytest.mark.parametrize("world", [1, 4])
def test_q3_sort_pass_bytes_reduction(world, devices):
    """Acceptance: q3 (join -> groupby-SUM) through order propagation runs
    with >= 30% fewer traced sort-pass bytes than the eager unordered path,
    identical output."""
    ctx = ct.CylonContext.init_distributed(
        ct.TPUConfig(devices=devices[:world])
    )
    rng = np.random.default_rng(16)
    n = 20000
    lt = ct.Table.from_pydict(ctx, {
        "k": rng.integers(0, n, n).astype(np.int32),
        "v": rng.normal(size=n).astype(np.float32),
    })
    rt = ct.Table.from_pydict(ctx, {
        "k": rng.integers(0, n, n).astype(np.int32),
        "w": rng.normal(size=n).astype(np.float32),
    })
    res = {}

    def q3_eager():
        res["e"] = lt.distributed_join(
            rt, on="k", how="inner"
        ).distributed_groupby("k_x", {"v": "sum"})

    def q3_ordered():
        res["o"] = lt.distributed_join(
            rt, on="k", how="inner", emit_order="key"
        ).distributed_groupby("k_x", {"v": "sum"})

    te = _sort_totals(q3_eager)
    to = _sort_totals(q3_ordered)
    assert to.sort_count < te.sort_count
    reduction = 1.0 - to.sort_pass_bytes / te.sort_pass_bytes
    assert reduction >= 0.30, (
        f"sort-pass bytes only reduced {reduction:.1%} "
        f"({te.sort_pass_bytes / 1e9:.3f} -> {to.sort_pass_bytes / 1e9:.3f} GB)"
    )
    pdt.assert_frame_equal(
        res["e"].to_pandas().sort_values("k_x").reset_index(drop=True),
        res["o"].to_pandas().sort_values("k_x").reset_index(drop=True),
    )


def test_explain_q3_shows_elided_lexsort(ctx4):
    """Acceptance: .explain() surfaces the order property per node and the
    elided groupby lexsort on the q3 plan (count agg — a shape the fused
    join+groupby rule does not take, so order_reuse carries it)."""
    rng = np.random.default_rng(17)
    lt, rt = _tables(ctx4, rng, n=2000)
    rt = rt.rename({"k": "rk"})
    lf = lt.lazy().join(
        rt.lazy(), left_on="k", right_on="rk", how="inner"
    ).groupby("k", {"v": "count"})
    text = lf.explain()
    assert plan_rules.ORDER_REUSE in text
    assert "emit=key-order" in text
    assert "lexsort elided" in text
    assert "-- order:" in text  # per-node order property
    # the rewritten plan computes the same thing
    got = lf.collect().to_pandas().sort_values("k").reset_index(drop=True)
    want = (
        lt.distributed_join(rt, left_on=["k"], right_on=["rk"], how="inner")
        .distributed_groupby("k", {"v": "count"})
        .to_pandas().sort_values("k").reset_index(drop=True)
    )
    pdt.assert_frame_equal(got, want)


def test_explain_global_sort_elision_over_range_shuffle(ctx4):
    """At world > 1 the planner's Sort physicalizes a range Shuffle under
    itself; when the shuffle's input already holds the requested order at
    GLOBAL scope, order_reuse drops BOTH (the eager distributed_sort no-op
    lifted into the plan)."""
    rng = np.random.default_rng(20)
    lt, _ = _tables(ctx4, rng, n=2000)
    s = lt.distributed_sort("v")
    assert s.ordering is not None and s.ordering.scope == "global"
    text = s.lazy().sort("v").explain()
    assert plan_rules.ORDER_REUSE in text
    opt = text.split("== Optimized plan ==")[1]
    assert "Sort" not in opt and "Shuffle" not in opt
    pdt.assert_frame_equal(
        s.lazy().sort("v").collect().to_pandas(), s.to_pandas()
    )
    # an unsorted input keeps both nodes
    text2 = lt.lazy().sort("v").explain()
    opt2 = text2.split("== Optimized plan ==")[1]
    assert "Sort" in opt2 and "Shuffle range" in opt2


def test_explain_sort_elision_rewrite(ctx1):
    rng = np.random.default_rng(18)
    lt, _ = _tables(ctx1, rng, n=300)
    s = lt.sort("k")
    text = s.lazy().sort("k").explain()
    assert plan_rules.ORDER_REUSE in text
    # the optimized plan has no Sort node left
    opt = text.split("== Optimized plan ==")[1]
    assert "Sort" not in opt
    pdt.assert_frame_equal(
        s.lazy().sort("k").collect().to_pandas(), s.to_pandas()
    )


def test_escape_hatch_gates_plan_rewrites(ctx4):
    """CYLON_TPU_NO_ORDERING=1 must disable the order_reuse rewrites too
    (not just the eager kernel gates), and the plan cache must not alias
    executors across gate states."""
    rng = np.random.default_rng(21)
    lt, rt = _tables(ctx4, rng, n=1000)
    rt = rt.rename({"k": "rk"})
    lf = lt.lazy().join(
        rt.lazy(), left_on="k", right_on="rk", how="inner"
    ).groupby("k", {"v": "count"})
    assert plan_rules.ORDER_REUSE in lf.explain()
    with _gates_off():
        assert plan_rules.ORDER_REUSE not in lf.explain()
        off = lf.collect().to_pandas().sort_values("k").reset_index(drop=True)
    on = lf.collect().to_pandas().sort_values("k").reset_index(drop=True)
    pdt.assert_frame_equal(on, off)


def test_plan_cache_keyed_by_input_ordering(ctx1):
    """Two same-shape plans over inputs that differ ONLY in their ordering
    descriptor must not alias in the plan-fingerprint cache (the rewrites
    consumed the descriptor)."""
    rng = np.random.default_rng(19)
    lt, _ = _tables(ctx1, rng, n=300)
    s = lt.sort("k")
    f1 = lt.lazy().sort("k").plan.fingerprint()
    f2 = s.lazy().sort("k").plan.fingerprint()
    assert f1 != f2
