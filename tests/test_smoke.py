"""End-to-end smoke: construction, join, groupby, sort vs pandas oracles."""
import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct


def _df(rng, n, keyspace=10):
    return pd.DataFrame(
        {
            "k": rng.integers(0, keyspace, n),
            "v": rng.normal(size=n),
        }
    )


def test_roundtrip(world_ctx, rng):
    df = _df(rng, 37)
    t = ct.Table.from_pandas(world_ctx, df)
    assert t.row_count == 37
    assert t.column_names == ["k", "v"]
    back = t.to_pandas()
    pd.testing.assert_frame_equal(back, df, check_dtype=False)


def test_local_join_inner(world_ctx, rng):
    # per-shard local join: oracle is pandas merge per shard partition
    l = _df(rng, 23)
    r = _df(rng, 17)
    tl = ct.Table.from_pandas(world_ctx, l)
    tr = ct.Table.from_pandas(world_ctx, r)
    out = tl.join(tr, on="k", how="inner")
    # reconstruct expected by per-shard pandas merges
    world = world_ctx.world_size
    lparts = np.array_split(l, world) if world > 1 else [l]
    rparts = np.array_split(r, world) if world > 1 else [r]
    # from_pandas splits evenly: base + remainder pattern
    def split(df):
        n = len(df)
        base, rem = divmod(n, world)
        sizes = [base + (1 if i < rem else 0) for i in range(world)]
        outp, off = [], 0
        for s in sizes:
            outp.append(df.iloc[off : off + s])
            off += s
        return outp

    exp = pd.concat(
        [lp.merge(rp, on="k", how="inner") for lp, rp in zip(split(l), split(r))]
    )
    # Table.join keeps both key columns with suffixes (reference semantics)
    got = out.to_pandas().rename(columns={"k_x": "k"}).drop(columns=["k_y"])
    assert len(got) == len(exp)
    key_cols = ["k", "v_x", "v_y"]
    got_s = got.sort_values(key_cols).reset_index(drop=True)
    exp_s = exp.sort_values(key_cols).reset_index(drop=True)
    pd.testing.assert_frame_equal(got_s, exp_s, check_dtype=False)


def test_distributed_join_inner(world_ctx, rng):
    l = _df(rng, 50)
    r = _df(rng, 40)
    tl = ct.Table.from_pandas(world_ctx, l)
    tr = ct.Table.from_pandas(world_ctx, r)
    out = tl.distributed_join(tr, on="k", how="inner")
    exp = l.merge(r, on="k", how="inner")
    got = out.to_pandas().rename(columns={"k_x": "k"}).drop(columns=["k_y"])
    assert len(got) == len(exp)
    cols = ["k", "v_x", "v_y"]
    pd.testing.assert_frame_equal(
        got.sort_values(cols).reset_index(drop=True),
        exp.sort_values(cols).reset_index(drop=True),
        check_dtype=False,
    )


@pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
def test_distributed_join_types(ctx8, rng, how):
    l = _df(rng, 60, keyspace=15)
    r = _df(rng, 45, keyspace=15)
    tl = ct.Table.from_pandas(ctx8, l)
    tr = ct.Table.from_pandas(ctx8, r)
    out = tl.distributed_join(tr, on="k", how=how)
    exp = l.merge(r, on="k", how=how)
    got = out.to_pandas()
    assert len(got) == len(exp)
    # for outer joins the key column may be null on one side; compare k from
    # coalesced representation
    cols = ["v_x", "v_y"]
    pd.testing.assert_frame_equal(
        got.sort_values(cols).reset_index(drop=True)[cols],
        exp.sort_values(cols).reset_index(drop=True)[cols],
        check_dtype=False,
    )


def test_distributed_sort(world_ctx, rng):
    df = _df(rng, 101, keyspace=1000)
    t = ct.Table.from_pandas(world_ctx, df)
    out = t.distributed_sort("k")
    got = out.to_pandas()
    assert len(got) == len(df)
    assert (np.diff(got["k"].to_numpy()) >= 0).all()
    np.testing.assert_allclose(
        np.sort(got["v"].to_numpy()), np.sort(df["v"].to_numpy())
    )


def test_distributed_groupby(world_ctx, rng):
    df = _df(rng, 97)
    t = ct.Table.from_pandas(world_ctx, df)
    out = t.distributed_groupby("k", {"v": ["sum", "mean", "count"]})
    got = out.to_pandas().sort_values("k").reset_index(drop=True)
    exp = (
        df.groupby("k")["v"]
        .agg(["sum", "mean", "count"])
        .reset_index()
        .rename(columns={"sum": "v_sum", "mean": "v_mean", "count": "v_count"})
    )
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_set_ops(ctx8, rng):
    a = pd.DataFrame({"x": rng.integers(0, 20, 30), "y": rng.integers(0, 3, 30)})
    b = pd.DataFrame({"x": rng.integers(0, 20, 25), "y": rng.integers(0, 3, 25)})
    ta = ct.Table.from_pandas(ctx8, a)
    tb = ct.Table.from_pandas(ctx8, b)

    def rows(df):
        return set(map(tuple, df.to_numpy()))

    got_u = rows(ta.distributed_union(tb).to_pandas())
    assert got_u == rows(a) | rows(b)
    got_i = rows(ta.distributed_intersect(tb).to_pandas())
    assert got_i == rows(a) & rows(b)
    got_s = rows(ta.distributed_subtract(tb).to_pandas())
    assert got_s == rows(a) - rows(b)


def test_scalar_aggregates(world_ctx, rng):
    df = _df(rng, 64)
    t = ct.Table.from_pandas(world_ctx, df)
    assert t.count("v") == 64
    np.testing.assert_allclose(t.sum("v"), df["v"].sum())
    np.testing.assert_allclose(t.min("v"), df["v"].min())
    np.testing.assert_allclose(t.max("v"), df["v"].max())
    np.testing.assert_allclose(t.mean("v"), df["v"].mean())


def test_string_columns(ctx8, rng):
    a = pd.DataFrame(
        {"s": rng.choice(["apple", "pear", "fig"], 20), "v": rng.normal(size=20)}
    )
    b = pd.DataFrame(
        {"s": rng.choice(["pear", "fig", "kiwi"], 15), "w": rng.normal(size=15)}
    )
    ta = ct.Table.from_pandas(ctx8, a)
    tb = ct.Table.from_pandas(ctx8, b)
    out = (
        ta.distributed_join(tb, on="s", how="inner")
        .to_pandas()
        .rename(columns={"s_x": "s"})
        .drop(columns=["s_y"])
    )
    exp = a.merge(b, on="s", how="inner")
    assert len(out) == len(exp)
    cols = ["s", "v", "w"]
    pd.testing.assert_frame_equal(
        out.sort_values(cols).reset_index(drop=True),
        exp.sort_values(cols).reset_index(drop=True),
        check_dtype=False,
    )


def test_filter_and_project(world_ctx, rng):
    df = _df(rng, 40)
    t = ct.Table.from_pandas(world_ctx, df)
    out = t.select(lambda c: c["v"] > 0.0).to_pandas()
    exp = df[df["v"] > 0.0].reset_index(drop=True)
    assert len(out) == len(exp)
    pd.testing.assert_frame_equal(
        out.sort_values(["k", "v"]).reset_index(drop=True),
        exp.sort_values(["k", "v"]).reset_index(drop=True),
        check_dtype=False,
    )
    p = t.project(["v"])
    assert p.column_names == ["v"]


def test_unique(ctx8, rng):
    df = pd.DataFrame({"x": rng.integers(0, 10, 50)})
    t = ct.Table.from_pandas(ctx8, df)
    got = t.distributed_unique().to_pandas()
    assert set(got["x"]) == set(df["x"])
    assert len(got) == df["x"].nunique()


def test_distributed_sort_huge_f64_keys(ctx8):
    """Range partition sentinel must dominate f64 keys beyond f32 range."""
    import pandas as pd

    vals = np.array([1e40, -2e40, 3.5e38, -3.5e38, 0.0, 7e39, 1.0, -1.0] * 4)
    t = ct.Table.from_pandas(ctx8, pd.DataFrame({"v": vals}))
    out = t.distributed_sort("v").to_pandas()["v"].to_numpy()
    assert (np.diff(out) >= 0).all()
    assert np.allclose(np.sort(vals), out)
