"""Semi-join sketch filtering (ISSUE 4 tentpole).

Pins the tentpole's observable contracts:

- DIFFERENTIAL IDENTITY: filtered and unfiltered (CYLON_TPU_NO_SEMI_FILTER=1)
  distributed joins and set ops produce identical output across
  inner/left/right/outer and intersect/subtract/union at world in {1, 4, 8}
  — including null keys (the audit: this engine's joins follow pandas
  merge, null == null MATCHES, so nulls are sketched as values and must
  never be pruned against a side that may hold a null) and
  dictionary-encoded string keys (probed on post-unification CODES).
- the sketch has NO FALSE NEGATIVES (unit level), the range words prune
  disjoint key ranges even when the Bloom saturates, and outer joins
  provably never build a sketch.
- collective accounting: a filtered distributed join traces exactly
  2 payload all_to_alls + 1 sketch all_gather, with the sketch's bytes
  bounded by the CYLON_TPU_SKETCH_BITS knob.
- the adaptive gate skips the filter when measured selectivity says it
  will not pay, and the host size gate skips tiny tables entirely.
- the plan layer annotates eligible Joins (explain + fingerprint), and the
  dictionary fast-path precondition (single mask-free int32 code column)
  survives the filtered shuffle.
"""
import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.ops import sketch as _sk
from cylon_tpu.utils.tracing import get_count, reset_trace


def _ctx(devices, world):
    return ct.CylonContext.init_distributed(
        ct.TPUConfig(devices=devices[:world])
    )


def _norm(df, cols=None):
    cols = list(df.columns) if cols is None else cols
    out = df.copy()
    for c in out.columns:
        if out[c].dtype == object:
            out[c] = out[c].map(lambda v: "\x00null" if v is None else str(v))
    out = out.fillna("\x00null").astype(str)
    return out.sort_values(cols, kind="mergesort").reset_index(drop=True)


def _assert_same(a, b):
    da, db = _norm(a.to_pandas()), _norm(b.to_pandas())
    assert list(da.columns) == list(db.columns)
    pd.testing.assert_frame_equal(da, db)


def _selective_pair(ctx, rng, n, dtype="int32", null_p=0.0):
    """~10%-overlap keyspaces: left in [0, K), right in [0.9K, 1.9K)."""
    K = 6 * n
    lk = rng.integers(0, K, n)
    rk = rng.integers(int(0.9 * K), int(1.9 * K), n)
    if dtype == "str":
        lk = np.array([f"s{v:07d}" for v in lk], dtype=object)
        rk = np.array([f"s{v:07d}" for v in rk], dtype=object)
    else:
        lk = lk.astype(dtype)
        rk = rk.astype(dtype)
    if null_p:
        lk = lk.astype(object)
        rk = rk.astype(object)
        lk[rng.random(n) < null_p] = None
        rk[rng.random(n) < null_p] = None
    # three payload columns per side: the size gate correctly skips
    # key-plus-one-value tables this small (per-shard payload < 2x sketch
    # bytes), and realistic join inputs carry payload anyway
    lt = ct.Table.from_pandas(ctx, pd.DataFrame(
        {"k": lk, "v": rng.normal(size=n).astype(np.float32),
         "v1": rng.normal(size=n).astype(np.float32),
         "v2": rng.normal(size=n).astype(np.float32)}
    ))
    rt = ct.Table.from_pandas(ctx, pd.DataFrame(
        {"k": rk, "w": rng.normal(size=n).astype(np.float32),
         "w1": rng.normal(size=n).astype(np.float32),
         "w2": rng.normal(size=n).astype(np.float32)}
    ))
    return lt, rt


# ----------------------------------------------------------------------
# unit level: no false negatives; range pruning
# ----------------------------------------------------------------------
def test_sketch_no_false_negatives(rng):
    import jax.numpy as jnp

    keys = rng.integers(-50_000, 50_000, 4000).astype(np.int32)
    cols = [(jnp.asarray(keys), None)]
    n = jnp.asarray(len(keys), jnp.int32)
    local = _sk.build_local(cols, n, bits=65536, use_range=True)
    hits = np.asarray(_sk.probe(cols, local, use_range=True))
    # every inserted key must survive its own sketch
    assert bool(hits.all())


def test_sketch_range_prunes_saturated_bloom(rng):
    """Disjoint key ranges stay pruned by the min/max words even when the
    Bloom is totally saturated (tiny bits, many keys)."""
    import jax.numpy as jnp

    build = rng.integers(0, 1_000_000, 50_000).astype(np.int32)
    local = _sk.build_local(
        [(jnp.asarray(build), None)], jnp.asarray(len(build), jnp.int32),
        bits=4096, use_range=True,
    )
    probe_keys = rng.integers(2_000_000, 3_000_000, 1000).astype(np.int32)
    hits = np.asarray(_sk.probe(
        [(jnp.asarray(probe_keys), None)], local, use_range=True
    ))
    assert not hits.any(), "range words must prune disjoint key ranges"


def test_sketch_empty_build_side_prunes_everything(rng):
    import jax.numpy as jnp

    empty = jnp.zeros((64,), jnp.int32)
    local = _sk.build_local(
        [(empty, None)], jnp.asarray(0, jnp.int32), bits=4096, use_range=True
    )
    keys = rng.integers(0, 100, 500).astype(np.int32)
    hits = np.asarray(_sk.probe(
        [(jnp.asarray(keys), None)], local, use_range=True
    ))
    assert not hits.any()


# ----------------------------------------------------------------------
# differential identity: joins
# ----------------------------------------------------------------------
@pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
def test_join_differential(world_ctx, rng, how):
    ctx = world_ctx
    lt, rt = _selective_pair(ctx, rng, 4000)
    got = lt.distributed_join(rt, on="k", how=how)
    with _sk.disabled():
        want = lt.distributed_join(rt, on="k", how=how)
    _assert_same(got, want)


@pytest.mark.parametrize("how", ["inner", "left"])
def test_join_differential_null_keys(devices, rng, how):
    """The null-key audit (pinned): this engine's joins MATCH null keys
    (pandas merge semantics — null x null pairs emit), so the sketch
    treats null as a value; a filter that dropped null-key rows would
    delete real output rows, which this differential would catch."""
    for world in (4, 8):
        ctx = _ctx(devices, world)
        lt, rt = _selective_pair(ctx, rng, 3000, null_p=0.1)
        got = lt.distributed_join(rt, on="k", how=how)
        with _sk.disabled():
            want = lt.distributed_join(rt, on="k", how=how)
        # null x null matches must be present in BOTH outputs
        assert got.row_count > 3000 * 3000 * 0.005  # ~ (0.1 * 3000)^2
        _assert_same(got, want)


def test_null_keys_pruned_against_null_free_side(devices, rng):
    """Null keys have no partner when the OTHER side holds no nulls: the
    sketch prunes them (nulls-last sentinel + null-as-zero hash identity)
    and the output is still identical."""
    ctx = _ctx(devices, 8)
    n = 4000
    lk = rng.integers(0, 24000, n).astype(object)
    lk[rng.random(n) < 0.3] = None
    lt = ct.Table.from_pandas(ctx, pd.DataFrame(
        {"k": lk, "v": rng.normal(size=n).astype(np.float32),
         "v1": rng.normal(size=n).astype(np.float32)}
    ))
    rt = ct.Table.from_pandas(ctx, pd.DataFrame(
        {"k": rng.integers(22000, 46000, n).astype(object),
         "w": rng.normal(size=n).astype(np.float32),
         "w1": rng.normal(size=n).astype(np.float32)}
    ))
    reset_trace()
    got = lt.distributed_join(rt, on="k", how="inner")
    pruned = get_count("shuffle.semi_filter.pruned_rows")
    with _sk.disabled():
        want = lt.distributed_join(rt, on="k", how="inner")
    _assert_same(got, want)
    assert pruned > 0


def test_join_differential_dict_string_keys(devices, rng):
    """Dictionary-encoded string keys filter on post-unification CODES:
    the two sides' dictionaries differ until _unify_dict_pair, after which
    equal strings share a code — the differential pins that the code-level
    sketch never prunes a real string match."""
    ctx = _ctx(devices, 8)
    lt, rt = _selective_pair(ctx, rng, 4000, dtype="str")
    assert lt.column("k").dtype.is_dictionary
    reset_trace()
    got = lt.distributed_join(rt, on="k", how="inner")
    assert get_count("shuffle.semi_filter.applied") >= 1
    with _sk.disabled():
        want = lt.distributed_join(rt, on="k", how="inner")
    _assert_same(got, want)


def test_dict_fast_path_survives_filtered_shuffle(devices, rng):
    """The join's fused uint32 string fast path needs a single MASK-FREE
    int32 code column; the filtered shuffle must not manufacture a
    validity mask on it (the lane-plan compact keeps mask-free columns
    mask-free)."""
    from cylon_tpu.ops.join import _fast_path_ok
    from cylon_tpu.table import _shuffle_pair, _unify_dict_pair

    ctx = _ctx(devices, 8)
    lt, rt = _selective_pair(ctx, rng, 4000, dtype="str")
    a, b = _unify_dict_pair(lt, rt, ["k"], ["k"])
    reset_trace()
    asf, bsf = _shuffle_pair(a, ["k"], b, ["k"], semi="both")
    assert get_count("shuffle.semi_filter.applied") == 2
    for t in (asf, bsf):
        c = t.column("k")
        assert c.dtype.is_dictionary
        assert _fast_path_ok([(c.data, c.valid)]), (
            "filtered shuffle broke the uint32 fast-path precondition"
        )


# ----------------------------------------------------------------------
# differential identity: set ops
# ----------------------------------------------------------------------
@pytest.mark.parametrize("op", ["intersect", "subtract", "union"])
def test_setop_differential(world_ctx, rng, op):
    ctx = world_ctx
    n = 4000
    la = ct.Table.from_pydict(ctx, {
        "k": rng.integers(0, 12000, n).astype(np.int32),
        "g": rng.integers(0, 3, n).astype(np.int32),
        "x": (rng.integers(0, 40, n) * 0.25).astype(np.float32),
    })
    lb = ct.Table.from_pydict(ctx, {
        "k": rng.integers(10000, 22000, n).astype(np.int32),
        "g": rng.integers(0, 3, n).astype(np.int32),
        "x": (rng.integers(0, 40, n) * 0.25).astype(np.float32),
    })
    got = getattr(la, f"distributed_{op}")(lb)
    with _sk.disabled():
        want = getattr(la, f"distributed_{op}")(lb)
    _assert_same(got, want)


def test_setop_differential_null_rows(devices, rng):
    """Set-op equality treats null == null: a right-side null row must keep
    left null rows alive through the subtract filter."""
    ctx = _ctx(devices, 4)
    n = 3000
    lk = rng.integers(0, 9000, n).astype(object)
    lk[rng.random(n) < 0.2] = None
    rk = rng.integers(8000, 17000, n).astype(object)
    rk[rng.random(n) < 0.2] = None
    la = ct.Table.from_pandas(ctx, pd.DataFrame({"k": lk}))
    lb = ct.Table.from_pandas(ctx, pd.DataFrame({"k": rk}))
    for op in ("intersect", "subtract"):
        got = getattr(la, f"distributed_{op}")(lb)
        with _sk.disabled():
            want = getattr(la, f"distributed_{op}")(lb)
        _assert_same(got, want)
    # intersect keeps exactly one null row (nulls present on both sides)
    vals = la.distributed_intersect(lb).to_pandas()["k"]
    assert vals.isna().sum() == 1


def test_union_never_filters(devices, rng):
    """Union emits every distinct row of both sides — nothing may be
    pruned, so no sketch is ever built."""
    ctx = _ctx(devices, 8)
    la = ct.Table.from_pydict(
        ctx, {"k": rng.integers(0, 50000, 4000).astype(np.int32)}
    )
    lb = ct.Table.from_pydict(
        ctx, {"k": rng.integers(45000, 95000, 4000).astype(np.int32)}
    )
    reset_trace()
    la.distributed_union(lb)
    assert get_count("semi_filter.sketch_bytes") == 0


# ----------------------------------------------------------------------
# gating
# ----------------------------------------------------------------------
def test_outer_join_provably_skips(devices, rng):
    assert _sk.join_filter_sides("outer") is None
    assert _sk.join_filter_sides("fullouter") is None
    ctx = _ctx(devices, 8)
    lt, rt = _selective_pair(ctx, rng, 4000)
    reset_trace()
    lt.distributed_join(rt, on="k", how="outer")
    assert get_count("semi_filter.sketch_bytes") == 0
    assert get_count("shuffle.semi_filter.applied") == 0


def test_adaptive_gate_skips_unselective_filter(devices, rng):
    """Fully-overlapping keyspaces: the count phase measures ~1.0
    selectivity and the gate packs unfiltered — same output."""
    ctx = _ctx(devices, 8)
    n = 6000
    lt = ct.Table.from_pydict(ctx, {
        "k": rng.integers(0, 500, n).astype(np.int32),
        "v": rng.normal(size=n).astype(np.float32),
        "v1": rng.normal(size=n).astype(np.float32),
        "v2": rng.normal(size=n).astype(np.float32),
    })
    rt = ct.Table.from_pydict(ctx, {
        "k": rng.integers(0, 500, n).astype(np.int32),
        "w": rng.normal(size=n).astype(np.float32),
        "w1": rng.normal(size=n).astype(np.float32),
        "w2": rng.normal(size=n).astype(np.float32),
    })
    reset_trace()
    got = lt.distributed_join(rt, on="k", how="inner")
    assert get_count("shuffle.semi_filter.gate_skipped") == 2
    assert get_count("shuffle.semi_filter.applied") == 0
    with _sk.disabled():
        want = lt.distributed_join(rt, on="k", how="inner")
    _assert_same(got, want)


def test_size_gate_skips_tiny_tables(devices, rng):
    """Tables whose exchange payload cannot repay the sketch collective
    never build one (config.SEMI_FILTER_MIN_PAYOFF)."""
    ctx = _ctx(devices, 8)
    lt = ct.Table.from_pydict(
        ctx, {"k": rng.integers(0, 100, 64).astype(np.int32)}
    )
    rt = ct.Table.from_pydict(
        ctx, {"k": rng.integers(900, 1000, 64).astype(np.int32)}
    )
    reset_trace()
    lt.distributed_join(rt, on="k", how="inner")
    assert get_count("semi_filter.sketch_bytes") == 0


def test_world_one_no_filter(devices, rng):
    ctx = _ctx(devices, 1)
    lt, rt = _selective_pair(ctx, rng, 4000)
    reset_trace()
    got = lt.distributed_join(rt, on="k", how="inner")
    assert get_count("semi_filter.sketch_bytes") == 0
    with _sk.disabled():
        want = lt.distributed_join(rt, on="k", how="inner")
    _assert_same(got, want)


# ----------------------------------------------------------------------
# collective accounting + knobs
# ----------------------------------------------------------------------
def test_filtered_join_collectives_and_sketch_bytes(devices, rng, monkeypatch):
    """A filtered distributed join traces exactly 2 payload all_to_alls +
    1 sketch all_gather, and the sketch program's collective bytes respect
    the CYLON_TPU_SKETCH_BITS cap."""
    from benchmarks.roofline import traced_collectives

    monkeypatch.setenv("CYLON_TPU_SKETCH_BITS", "32768")
    ctx = _ctx(devices, 8)
    lt, rt = _selective_pair(ctx, rng, 4000)
    colls, per_bytes = traced_collectives(
        lambda: lt.distributed_join(rt, on="k", how="inner")
    )
    from cylon_tpu.analysis import contracts

    expect = (
        contracts.DIST_JOIN_PAYLOAD_COLLECTIVES
        + contracts.DIST_JOIN_SKETCH_COLLECTIVES
    )
    assert colls == expect, (
        f"expected {expect} (2 payload + 1 sketch) collectives, got {colls}"
    )
    cap_bytes = 2 * _sk.sketch_len(32768) * 4
    assert min(per_bytes) <= cap_bytes, (per_bytes, cap_bytes)


def test_sketch_bytes_counter_and_knob(devices, rng, monkeypatch):
    monkeypatch.setenv("CYLON_TPU_SKETCH_BITS", "16384")
    ctx = _ctx(devices, 8)
    lt, rt = _selective_pair(ctx, rng, 4000)
    reset_trace()
    lt.distributed_join(rt, on="k", how="inner")
    from cylon_tpu.utils.tracing import report

    wire = report("semi_filter.")["semi_filter.sketch_bytes"]["rows"]
    assert wire == 2 * _sk.sketch_len(16384) * 4


def test_selectivity_gauge_recorded(devices, rng):
    ctx = _ctx(devices, 8)
    lt, rt = _selective_pair(ctx, rng, 4000)
    reset_trace()
    lt.distributed_join(rt, on="k", how="inner")
    from cylon_tpu.utils.tracing import report

    g = report("shuffle.semi_filter.")["shuffle.semi_filter.selectivity"]
    assert g["count"] == 2  # one sample per filtered side
    mean_sel = g["total_s"] / g["count"]
    assert 0.0 < mean_sel < 0.5  # ~10% true selectivity + bloom FP


# ----------------------------------------------------------------------
# plan layer
# ----------------------------------------------------------------------
def test_plan_annotates_and_lowers_semi_filter(devices, rng):
    ctx = _ctx(devices, 8)
    lt, rt = _selective_pair(ctx, rng, 4000)
    lf = lt.lazy().join(rt.lazy(), on="k", how="inner")
    exp = lf.explain()
    assert "semi-filter=both" in exp
    reset_trace()
    got = lf.collect()
    assert get_count("shuffle.semi_filter.applied") == 2
    with _sk.disabled():
        exp_off = lt.lazy().join(rt.lazy(), on="k", how="inner").explain()
        assert "semi-filter" not in exp_off
        want = lt.lazy().join(rt.lazy(), on="k", how="inner").collect()
    _assert_same(got, want)


def test_plan_left_join_annotates_right_side(devices, rng):
    ctx = _ctx(devices, 8)
    lt, rt = _selective_pair(ctx, rng, 4000)
    exp = lt.lazy().join(rt.lazy(), on="k", how="left").explain()
    assert "semi-filter=right" in exp
    exp_outer = lt.lazy().join(rt.lazy(), on="k", how="outer").explain()
    assert "semi-filter" not in exp_outer


def test_plan_cache_keyed_by_semi_gate(devices, rng):
    """A cached executor compiled WITH the filter must not serve a collect
    under CYLON_TPU_NO_SEMI_FILTER=1 (the gate state is part of the plan
    fingerprint, like the ordering escape hatch)."""
    ctx = _ctx(devices, 8)
    lt, rt = _selective_pair(ctx, rng, 4000)

    def plan():
        return lt.lazy().join(rt.lazy(), on="k", how="inner")

    got = plan().collect()
    reset_trace()
    with _sk.disabled():
        want = plan().collect()
        assert get_count("shuffle.semi_filter.applied") == 0
        assert get_count("semi_filter.sketch_bytes") == 0
    _assert_same(got, want)


def test_fused_join_groupby_plan_filters(devices, rng, monkeypatch):
    # projection pushdown narrows the fused pair to k+v / k-only rows —
    # too narrow to repay a row-count-sized sketch (the size gate would
    # skip), so cap the sketch small via the knob, the user-facing lever
    # for exactly this shape
    monkeypatch.setenv("CYLON_TPU_SKETCH_BITS", "8192")
    ctx = _ctx(devices, 8)
    lt, rt = _selective_pair(ctx, rng, 4000)
    lf = (
        lt.lazy().join(rt.lazy(), on="k", how="inner")
        .groupby(["k_x"], {"v": "sum"})
    )
    exp = lf.explain()
    assert "FusedJoinGroupBySum" in exp and "semi-filter=both" in exp
    reset_trace()
    got = lf.collect()
    assert get_count("shuffle.semi_filter.applied") == 2
    with _sk.disabled():
        want = (
            lt.lazy().join(rt.lazy(), on="k", how="inner")
            .groupby(["k_x"], {"v": "sum"}).collect()
        )
    _assert_same(got, want)
