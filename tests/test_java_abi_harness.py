"""Runnable proof of the Java FFM binding's ABI contract (VERDICT round-2
item 5): a C harness performs the byte-identical downcall sequence
java/org/cylondata/cylontpu/Table.java emits — including the round-3
callback surface (select / filter / mapColumn) whose C function-pointer ABIs
match CylonTpu.java's upcall stubs — and asserts the results against pandas
oracles here.

Reference analog: the JNI-backed Java client
(java/src/main/java/org/cylondata/cylon/Table.java + Table.cpp). Note the
reference's filter/mapColumn/hashPartition THROW unSupportedException
(Table.java:156-226); this ABI implements them for real.
"""
import os
import subprocess
import sys
import sysconfig

import numpy as np
import pandas as pd
import pytest

from cylon_tpu import native

_SRC = os.path.join(
    os.path.dirname(native.__file__), "examples", "java_abi_harness.c"
)


def _build(tmp_path) -> str:
    exe = str(tmp_path / "java_abi_harness")
    r = subprocess.run(
        ["gcc", "-O2", _SRC, "-o", exe, "-ldl"],
        capture_output=True, text=True, timeout=120,
    )
    if r.returncode != 0:
        pytest.skip(f"harness build failed: {r.stderr[-300:]}")
    return exe


def _subprocess_env():
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        [repo] + [p for p in sys.path if p and p != repo]
    )
    env["CYLON_TPU_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    )
    env.pop("JAX_PLATFORMS", None)
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    env["LD_LIBRARY_PATH"] = os.pathsep.join(
        filter(None, [libdir, env.get("LD_LIBRARY_PATH", "")])
    )
    return env


def test_java_abi_sequence(tmp_path):
    so = native.build_capi()
    if so is None:
        pytest.skip("capi build failed (no libpython?)")
    exe = _build(tmp_path)

    rng = np.random.default_rng(11)
    l = pd.DataFrame({"k": rng.integers(0, 30, 240), "x": rng.normal(size=240)})
    r = pd.DataFrame({"k": rng.integers(0, 30, 180), "y": rng.normal(size=180)})
    lp, rp = str(tmp_path / "l.csv"), str(tmp_path / "r.csv")
    out = str(tmp_path / "out.csv")
    l.to_csv(lp, index=False)
    r.to_csv(rp, index=False)

    res = subprocess.run(
        [exe, so, lp, rp, out],
        capture_output=True, text=True, timeout=600, env=_subprocess_env(),
    )
    assert res.returncode == 0, (
        f"stdout={res.stdout}\nstderr={res.stderr[-2000:]}"
    )
    got = dict(
        line.split("=", 1)
        for line in res.stdout.splitlines()
        if "=" in line and not line.startswith("cylon_tpu.Table")
    )

    exp_join = l.merge(r, on="k")
    assert int(got["join_rows"]) == len(exp_join)
    assert int(got["join_cols"]) == 4  # k_x, x, k_y, y
    assert int(got["select_rows"]) == int((l["k"] % 2 == 0).sum())
    assert int(got["filter_rows"]) == int(got["select_rows"])
    assert int(got["map_rows"]) == len(l)
    assert int(got["partition_total"]) == len(l)
    assert int(got["merge_rows"]) == len(l)
    assert got["ok"] == "1"

    # the written join matches pandas
    written = pd.read_csv(out)
    assert len(written) == len(exp_join)
    assert np.isclose(written["x"].sum(), exp_join["x"].sum())
