"""The shipped examples run end-to-end on the virtual mesh.

Reference analog: python/test/test_uno_app.py — an end-to-end application
test over the public API (SURVEY.md §4.2). Sizes are shrunk; the assertions
live inside the examples themselves (result checks, learnability check)."""
import numpy as np


def test_etl_logreg_end_to_end(devices):
    from examples.etl_logreg import main

    loss, acc = main(n_tx=30_000, n_users=3_000)
    assert np.isfinite(loss)
    assert acc > 0.85


def test_ooc_join_example_flow(devices):
    """examples/ooc_join.py's exact flow at test size: out-of-core join with
    bounded device allocations."""
    import cylon_tpu as ct
    from cylon_tpu.parallel.ooc import OutOfCoreJoin
    from examples.ooc_join import chunk_stream

    ctx = ct.CylonContext.init_distributed(ct.TPUConfig())
    n, chunk_rows = 40_000, 4_000
    job = OutOfCoreJoin(ctx, on="k", how="inner", num_buckets=16)
    sink = job.execute(
        chunk_stream(np.random.default_rng(0), n, chunk_rows, "x"),
        chunk_stream(np.random.default_rng(1), n, chunk_rows, "y"),
    )
    assert sink.rows > 0
    assert job.max_device_cap < n // ctx.world_size


def test_join_groupby_example_flow(devices):
    # the example's exact flow at test size (the 1M-row original is the
    # bench config; this keeps the suite fast)
    import pandas as pd

    import cylon_tpu as ct

    env = ct.CylonEnv(config=ct.TPUConfig())
    rng = np.random.default_rng(0)
    n = 20_000
    orders = pd.DataFrame(
        {"cust": rng.integers(0, 500, n), "price": rng.gamma(2.0, 50.0, n)}
    )
    customers = pd.DataFrame(
        {"cust": np.arange(500), "segment": rng.choice(list("abc"), 500)}
    )
    joined = ct.DataFrame(orders).merge(ct.DataFrame(customers), on="cust", env=env)
    assert len(joined) == n
    by_seg = joined.groupby("segment", env=env).agg({"price": "sum"})
    got = by_seg.to_pandas().sort_values("segment")["price_sum"].to_numpy()
    want = (
        orders.assign(segment=customers.set_index("cust").loc[orders.cust, "segment"].values)
        .groupby("segment")["price"]
        .sum()
        .sort_index()
        .to_numpy()
    )
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_scale_join_example_flow(devices):
    """examples/scale_join.py's exact flow at test size: sliced fused join
    under skew, then groupby."""
    import pandas as pd

    import cylon_tpu as ct

    ctx = ct.CylonContext.init_distributed(ct.TPUConfig())
    rng = np.random.default_rng(0)
    n = 20_000
    orders = pd.DataFrame({
        "cust": rng.integers(0, n // 4, n).astype(np.int32),
        "price": rng.gamma(2.0, 50.0, n).astype(np.float32),
    })
    orders.loc[rng.random(n) < 0.2, "cust"] = 7
    custs = pd.DataFrame({
        "cust": np.arange(n // 4, dtype=np.int32),
        "region": rng.integers(0, 50, n // 4).astype(np.int32),
    })
    joined = ct.Table.from_pandas(ctx, orders).distributed_join(
        ct.Table.from_pandas(ctx, custs),
        on="cust", mode="fused", num_slices=4, respill=2,
    )
    expect = orders.merge(custs, on="cust")
    assert joined.row_count == len(expect)
    # value-level check through the example's groupby: a row-count-preserving
    # mispairing in the sliced path would corrupt these sums
    got = (
        joined.distributed_groupby("region", {"price": "sum"})
        .to_pandas()
        .sort_values("region")
        .reset_index(drop=True)
    )
    want = (
        expect.groupby("region", as_index=False)["price"]
        .sum()
        .sort_values("region")
        .reset_index(drop=True)
    )
    assert (got["region"].to_numpy() == want["region"].to_numpy()).all()
    np.testing.assert_allclose(
        got["price_sum"].to_numpy(), want["price"].to_numpy(), rtol=1e-3
    )
