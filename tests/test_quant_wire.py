"""Quantized float wire tier tests (the lossy lane codec, ops/quant.py).

Four layers:
  1. codec round trips — q8/qb16/qf32 error bounds (negative values,
     inf/NaN passthrough, all-zero blocks) and the lossless h16 satellite
     (f16/bf16 at native 16-bit wire width, bit-exact);
  2. differentials — quantized join / groupby-SUM / sort / shuffle vs
     the CYLON_TPU_NO_QUANT=1 oracle at worlds {1, 4, 8}: exact keys,
     group identity and row counts, per-value rel-err <= tolerance on
     float payload columns;
  3. gate pins — tolerance-unset results bit-identical to the kill
     switch (the wire tier adds NOTHING when off), the plan fingerprint
     carries the tolerance, and the kernel cache key carries the codec
     signature;
  4. the spill/relay tier — a tier-1/2 forced shuffle stages q8 bytes
     through the host arenas (uint8 storage + per-batch scales) and a
     one-hot skew shape relays quantized tails, both within the doubled-
     crossing error budget.
"""
import os
import sys

import numpy as np
import pandas as pd
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp

import cylon_tpu as ct
from cylon_tpu.ops import gather as gmod
from cylon_tpu.ops import quant as qmod
from cylon_tpu.utils.tracing import get_count, reset_trace

TOL = 1e-2


@pytest.fixture(scope="module")
def ctx1(devices):
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:1]))


@pytest.fixture(scope="module")
def ctx4(devices):
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:4]))


@pytest.fixture(scope="module")
def ctx8(devices):
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:8]))


@pytest.fixture(autouse=True)
def _clean_env():
    saved = {
        k: os.environ.get(k)
        for k in ("CYLON_TPU_QUANT_TOL", "CYLON_TPU_NO_QUANT",
                  "CYLON_TPU_SPILL_TIER")
    }
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _tol(tol):
    os.environ["CYLON_TPU_QUANT_TOL"] = str(tol)


# ----------------------------------------------------------------------
# 1. codec round trips
# ----------------------------------------------------------------------

def test_q8_round_trip_error_bound():
    rng = np.random.default_rng(0)
    x = (rng.normal(size=512) * 50).astype(np.float32)
    xj = jnp.asarray(x)
    s = qmod.safe_scale(qmod.block_maxabs(xj))
    sv = jnp.full(x.shape, s)
    back = np.asarray(qmod.decode_q8(qmod.encode_q8(xj, sv), sv, "float32"))
    bm = float(np.abs(x).max())
    assert np.abs(back - x).max() <= bm / 252 + 1e-7
    # negative values survive with the same bound
    assert (np.sign(back[np.abs(x) > bm / 100]) ==
            np.sign(x[np.abs(x) > bm / 100])).all()


def test_q8_specials_and_zero_block():
    x = jnp.asarray(
        np.array([0.0, -0.0, np.nan, np.inf, -np.inf], np.float32)
    )
    s = qmod.safe_scale(qmod.block_maxabs(x))
    assert float(s) == 1.0  # no finite magnitude: the zero-block scale
    sv = jnp.full(x.shape, s)
    back = np.asarray(qmod.decode_q8(qmod.encode_q8(x, sv), sv, "float32"))
    assert back[0] == 0.0 and back[1] == 0.0  # all-zero block is exact
    assert np.isnan(back[2])
    assert back[3] == np.inf and back[4] == -np.inf
    # numpy mirror is bit-identical on codes
    xn = np.asarray(x)
    codes_np = qmod.np_encode_q8(xn, 1.0)
    codes_dev = np.asarray(qmod.encode_q8(x, sv)).astype(np.uint8)
    assert (codes_np == codes_dev).all()
    assert np.array_equal(
        qmod.np_decode_q8(codes_np, 1.0, "float32"), back, equal_nan=True
    )


def test_qb16_qf32_round_trips():
    rng = np.random.default_rng(1)
    x = np.concatenate(
        [rng.normal(size=256) * 1e3, [np.nan, np.inf, -np.inf, 0.0]]
    ).astype(np.float64)
    xj = jnp.asarray(x)
    b16 = np.asarray(qmod.decode_qb16(qmod.encode_qb16(xj), "float64"))
    fin = np.isfinite(x)
    assert np.abs(b16[fin] - x[fin]).max() <= 2.0 ** -8 * np.abs(x[fin]).max()
    assert np.isnan(b16[~fin][0]) and b16[-3] == np.inf and b16[-2] == -np.inf
    f32 = np.asarray(qmod.decode_qf32(qmod.encode_qf32(xj), "float64"))
    assert np.abs(f32[fin] - x[fin]).max() <= 2.0 ** -23 * np.abs(x[fin]).max()


def test_codec_for_tiers():
    assert qmod.codec_for(np.float32, 0.0) is None
    assert qmod.codec_for(np.int32, 1.0) is None
    assert qmod.codec_for(np.float32, 1e-2) == "q8"
    assert qmod.codec_for(np.float32, 5e-3) == "qb16"
    assert qmod.codec_for(np.float32, 1e-4) is None
    assert qmod.codec_for(np.float64, 1e-4) == "qf32"
    assert qmod.codec_for(np.float64, 1e-8) is None
    assert qmod.codec_for(np.float16, 1e-2) == "q8"
    assert qmod.codec_for(np.float16, 5e-3) is None  # h16 already 16-bit


def test_h16_wire_field_lossless(ctx4):
    rng = np.random.default_rng(2)
    n = 2000
    df = pd.DataFrame({
        "k": rng.integers(0, 64, n).astype(np.int32),
        "rid": np.arange(n, dtype=np.int64),
    })
    df["h"] = rng.normal(size=n).astype(np.float16)
    t = ct.Table.from_pandas(ctx4, df)
    got = t.shuffle(["k"]).to_pandas().sort_values("rid")
    want = df.sort_values("rid")
    assert (got["h"].values == want["h"].values).all()
    assert (got["k"].values == want["k"].values).all()


def test_h16_field_in_plan():
    # two f16 columns: 2x16 lossless bits share ONE word where the
    # widened codec shipped two full f32-bitcast lanes (a LONE f16
    # correctly declines — 16 bits still occupy one 32-bit word)
    plan = gmod.lane_plan(
        [(jnp.zeros(8, jnp.float16), None),
         (jnp.zeros(8, jnp.bfloat16), None)]
    )
    wp = gmod.wire_plan(list(plan), [None, None])
    assert wp is not None and wp.n_words == 1
    assert [f.kind for f in wp.fields] == ["h16", "h16"]
    assert [f.cls for f in wp.fields] == ["float16", "bfloat16"]
    alone = gmod.wire_plan(list(plan[:1]), [None])
    assert alone is None


# ----------------------------------------------------------------------
# 2. differentials vs the CYLON_TPU_NO_QUANT=1 oracle
# ----------------------------------------------------------------------

def _pair(rng, n, dtype=np.float32):
    ldf = pd.DataFrame({
        "k": rng.integers(0, max(n // 20, 2), n).astype(np.int32),
        "v": (rng.normal(size=n) * 10).astype(dtype),
        "rid": np.arange(n, dtype=np.int64),
    })
    rdf = pd.DataFrame({
        "rk": rng.integers(0, max(n // 20, 2), n // 2).astype(np.int32),
        "w": (rng.normal(size=n // 2) * 10).astype(dtype),
        "sid": np.arange(n // 2, dtype=np.int64),
    })
    return ldf, rdf


def _join(ctx, ldf, rdf):
    lt = ct.Table.from_pandas(ctx, ldf)
    rt = ct.Table.from_pandas(ctx, rdf)
    out = lt.distributed_join(
        rt, left_on=["k"], right_on=["rk"], how="inner"
    ).to_pandas()
    return out.sort_values(["rid", "sid"]).reset_index(drop=True)


@pytest.mark.parametrize("world", [1, 4, 8])
def test_join_differential(world, devices, request):
    ctx = request.getfixturevalue(f"ctx{world}")
    rng = np.random.default_rng(world)
    ldf, rdf = _pair(rng, 3000)
    with qmod.disabled():
        exact = _join(ctx, ldf, rdf)
    _tol(TOL)
    got = _join(ctx, ldf, rdf)
    # exact row identity: join keys and row ids are NEVER quantized
    assert len(exact) == len(got)
    for c in ("k", "rid", "sid"):
        assert (exact[c].values == got[c].values).all()
    # float payloads: per-value relative error within tolerance
    for c in ("v", "w"):
        ref = np.abs(exact[c].values).max()
        assert np.abs(exact[c].values - got[c].values).max() <= TOL * ref


@pytest.mark.parametrize("world", [1, 4, 8])
def test_groupby_sum_differential(world, devices, request):
    ctx = request.getfixturevalue(f"ctx{world}")
    rng = np.random.default_rng(10 + world)
    n = 4000
    df = pd.DataFrame({
        "k": rng.integers(0, 100, n).astype(np.int32),
        "v": (rng.normal(size=n) * 5).astype(np.float32),
    })

    def gb():
        t = ct.Table.from_pandas(ctx, df)
        return (
            t.distributed_groupby(["k"], {"v": "sum"})
            .to_pandas().sort_values("k").reset_index(drop=True)
        )

    with qmod.disabled():
        exact = gb()
    _tol(TOL)
    got = gb()
    # group identity is exact; the summed payload is tolerance-bounded
    # (per-group sums accumulate per-value errors, so the bound scales
    # with the max group's magnitude sum)
    assert (exact["k"].values == got["k"].values).all()
    budget = TOL * np.abs(df["v"]).sum()
    assert np.abs(exact["v_sum"].values - got["v_sum"].values).max() <= budget


def test_sort_differential_keys_exact(ctx4):
    rng = np.random.default_rng(20)
    n = 3000
    df = pd.DataFrame({
        "k": rng.integers(-500, 500, n).astype(np.int32),
        "v": rng.normal(size=n).astype(np.float32),
    })

    def srt():
        return (
            ct.Table.from_pandas(ctx4, df).sort(["k"]).to_pandas()
            .reset_index(drop=True)
        )

    with qmod.disabled():
        exact = srt()
    _tol(TOL)
    got = srt()
    assert (exact["k"].values == got["k"].values).all()
    ref = np.abs(exact["v"].values).max()
    # local sorts do not ride the wire; only shuffled payloads quantize —
    # a 1-table local sort must stay exact
    assert np.abs(exact["v"].values - got["v"].values).max() <= TOL * ref


def test_f64_passthrough_leaves_wire(ctx4):
    """A quantized f64 column leaves the per-column passthrough
    collective AND meets its tier's bound."""
    rng = np.random.default_rng(30)
    n = 2000
    df = pd.DataFrame({
        "k": rng.integers(0, 64, n).astype(np.int32),
        "d": rng.normal(size=n).astype(np.float64),
        "rid": np.arange(n, dtype=np.int64),
    })

    def shuf():
        return (
            ct.Table.from_pandas(ctx4, df).shuffle(["k"]).to_pandas()
            .sort_values("rid").reset_index(drop=True)
        )

    with qmod.disabled():
        exact = shuf()
    for tol, bound in ((1e-2, 1e-2), (1e-6, 2.0 ** -23)):
        _tol(tol)
        got = shuf()
        assert (exact["rid"].values == got["rid"].values).all()
        rel = (
            np.abs(exact["d"].values - got["d"].values).max()
            / np.abs(exact["d"].values).max()
        )
        assert rel <= bound


# ----------------------------------------------------------------------
# 3. gate pins
# ----------------------------------------------------------------------

def test_knob_off_is_identical(ctx4):
    """Tolerance unset == kill switch == today's exact wire: results are
    BIT-identical and the quant gate never engages."""
    rng = np.random.default_rng(40)
    ldf, rdf = _pair(rng, 2000)
    reset_trace()
    base = _join(ctx4, ldf, rdf)
    assert get_count("shuffle.quant.applied") == 0
    _tol(TOL)
    os.environ["CYLON_TPU_NO_QUANT"] = "1"  # kill switch beats tolerance
    killed = _join(ctx4, ldf, rdf)
    assert get_count("shuffle.quant.applied") == 0
    for c in base.columns:
        assert (base[c].values == killed[c].values).all()


def test_config_zero_overrides_env():
    """An explicit per-context quant_tol=0 opts back into the exact wire
    even under a process-wide env tolerance (config > env, including
    falsy values)."""
    _tol(TOL)
    assert qmod.tolerance(None) == TOL
    assert qmod.tolerance("0") == 0.0
    assert qmod.tolerance(0.0) == 0.0
    assert qmod.tolerance("") == 0.0
    assert qmod.tolerance("5e-3") == 5e-3


def test_lane_pack_oracle_disables_quant(ctx4):
    """CYLON_TPU_NO_LANE_PACK=1 disables the whole wire codec, the lossy
    tier included — the packing differential oracle keeps isolating the
    codec even when a tolerance is set (matches the fused path's gated
    static_wire_plan)."""
    from cylon_tpu.ops import stats as stmod

    rng = np.random.default_rng(45)
    ldf, rdf = _pair(rng, 1500)
    _tol(TOL)
    with stmod.disabled():
        reset_trace()
        got = _join(ctx4, ldf, rdf)
        assert get_count("shuffle.quant.applied") == 0
        with qmod.disabled():
            exact = _join(ctx4, ldf, rdf)
    for c in got.columns:
        assert (exact[c].values == got[c].values).all()


def test_fingerprint_carries_tolerance(ctx1):
    from cylon_tpu.plan.lazy import gated_fingerprint

    t = ct.Table.from_pydict(
        ctx1, {"k": np.arange(8, dtype=np.int32),
               "v": np.ones(8, np.float32)}
    )
    plan = t.lazy().groupby("k", {"v": "sum"}).plan
    fp_off = gated_fingerprint(plan)
    _tol(TOL)
    fp_on = gated_fingerprint(plan)
    os.environ["CYLON_TPU_NO_QUANT"] = "1"
    fp_kill = gated_fingerprint(plan)
    assert fp_off != fp_on
    assert fp_kill != fp_on


def test_wire_plan_key_carries_codec():
    """The codec decision lands in the WirePlan the kernel cache keys
    carry — different tolerances must never alias one program."""
    plan = gmod.lane_plan(
        [(jnp.zeros(8, jnp.int32), None), (jnp.zeros(8, jnp.float32), None)]
    )
    stats = [("i32", 8), None]
    wp_q8 = gmod.wire_plan(list(plan), stats, quant=(None, "q8"))
    wp_b16 = gmod.wire_plan(list(plan), stats, quant=(None, "qb16"))
    wp_off = gmod.wire_plan(list(plan), stats, quant=None)
    assert wp_q8 != wp_b16
    assert wp_off is None or wp_off != wp_q8
    assert hash(wp_q8) != hash(wp_b16)  # both usable as cache-key parts


# ----------------------------------------------------------------------
# 4. quantized spill tiers + skew relay
# ----------------------------------------------------------------------

def test_quantized_spill_tier_differential(ctx4):
    rng = np.random.default_rng(50)
    n = 4000
    df = pd.DataFrame({
        "k": rng.integers(0, 64, n).astype(np.int32),
        "v": (rng.normal(size=n) * 7).astype(np.float32),
        "rid": np.arange(n, dtype=np.int64),
    })

    def shuf():
        return (
            ct.Table.from_pandas(ctx4, df).shuffle(["k"]).to_pandas()
            .sort_values("rid").reset_index(drop=True)
        )

    with qmod.disabled():
        exact = shuf()
    _tol(TOL)
    os.environ["CYLON_TPU_SPILL_TIER"] = "1"
    reset_trace()
    got = shuf()
    assert get_count("shuffle.spill.staged_rounds") >= 1
    assert get_count("shuffle.quant.spill_bytes_saved") >= 1
    assert (exact["rid"].values == got["rid"].values).all()
    assert (exact["k"].values == got["k"].values).all()
    ref = np.abs(exact["v"].values).max()
    # two lossy crossings (wire + arena restage) stay under the budget
    assert np.abs(exact["v"].values - got["v"].values).max() <= TOL * ref


def test_arena_stores_uint8(ctx4):
    """The spill arenas hold quantized BYTES, not floats — the ~4x
    budget stretch the tier exists for."""
    from cylon_tpu.parallel.spill import ShardArenaSink

    sink = ShardArenaSink(
        2,
        [("k", np.dtype(np.int32), False), ("v", np.dtype(np.uint8), False)],
        1,
        quant={1: np.dtype(np.float32)},
    )
    v = np.array([1.0, -2.0, 0.5], np.float32)
    sink.accept(None, [
        [(np.array([1, 2, 3], np.int32), None), (v, None)],
        [(np.array([4], np.int32), None), (np.array([9.0], np.float32), None)],
    ], np.array([3, 1]))
    assert sink.arenas[0]._bufs[1][0].dtype == np.uint8
    back = sink.dequantized_columns(0)[1][0]
    assert back.dtype == np.float32
    assert np.abs(back - v).max() <= np.abs(v).max() / 252 + 1e-7
    sink.close()


def test_skew_relay_quantized(ctx8):
    rng = np.random.default_rng(60)
    n = 8000
    k = np.where(
        rng.random(n) < 0.95, 0, rng.integers(1, 128, n)
    ).astype(np.int32)
    df = pd.DataFrame({
        "k": k,
        "v": (rng.normal(size=n) * 3).astype(np.float32),
        "rid": np.arange(n, dtype=np.int64),
    })

    def shuf():
        return (
            ct.Table.from_pandas(ctx8, df).shuffle(["k"]).to_pandas()
            .sort_values("rid").reset_index(drop=True)
        )

    with qmod.disabled():
        exact = shuf()
    _tol(TOL)
    reset_trace()
    got = shuf()
    assert get_count("shuffle.skew_split") >= 1
    assert get_count("shuffle.quant.relay_bytes_saved") >= 1
    assert (exact["rid"].values == got["rid"].values).all()
    ref = np.abs(exact["v"].values).max()
    assert np.abs(exact["v"].values - got["v"].values).max() <= TOL * ref
