"""pycylon Table surface breadth: where/mask, __getitem__/__setitem__,
iterrows, string astype, row-UDF select.

Reference analog: python/pycylon/data/table.pyx:1066-2411 (getitem/setitem
filters, where, iterrows, astype) and cpp table.cpp:504-529 (UDF Select with
a Row cursor, row.hpp:24-52). Oracle: pandas.
"""
import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct


@pytest.fixture
def tbl(world_ctx, rng):
    df = pd.DataFrame(
        {
            "a": rng.integers(0, 10, 60).astype(np.int64),
            "b": rng.normal(size=60),
            "s": rng.choice(["x", "y", "z"], 60),
        }
    )
    df.loc[5, "b"] = np.nan
    return ct.Table.from_pandas(world_ctx, df), df


def _sorted_eq(t, df):
    a = t.to_pandas().sort_values(list(df.columns)).reset_index(drop=True)
    b = df.sort_values(list(df.columns)).reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b, check_dtype=False)


def test_where_null(tbl):
    t, df = tbl
    cond = t["a"] > 4
    out = t.project(["a", "b"]).where(cond).to_pandas()
    exp = df[["a", "b"]].where(df["a"] > 4)
    assert np.allclose(out["b"].to_numpy(), exp["b"].to_numpy(), equal_nan=True)
    assert np.allclose(out["a"].to_numpy(), exp["a"].to_numpy(), equal_nan=True)


def test_where_other_scalar(tbl):
    t, df = tbl
    cond = t["a"] > 4
    out = t.project(["a"]).where(cond, -1).to_pandas()
    exp = df[["a"]].where(df["a"] > 4, -1)
    assert (out["a"].to_numpy() == exp["a"].to_numpy()).all()


def test_mask_scalar(tbl):
    t, df = tbl
    cond = t["a"] > 4
    out = t.project(["a"]).mask(cond, 0).to_pandas()
    exp = df[["a"]].mask(df["a"] > 4, 0)
    assert (out["a"].to_numpy() == exp["a"].to_numpy()).all()


def test_where_string_col(tbl):
    t, df = tbl
    cond = t["a"] > 4
    out = t.project(["s"]).where(cond, "none").to_pandas()
    exp = df[["s"]].where(df["a"] > 4, "none")
    assert (out["s"].to_numpy() == exp["s"].to_numpy()).all()


def test_getitem_forms(tbl):
    t, df = tbl
    assert t["a"].column_names == ["a"]
    assert t[["a", "s"]].column_names == ["a", "s"]
    filt = t[t["a"] > 4]
    assert filt.row_count == int((df["a"] > 4).sum())
    sl = t[10:20]
    assert sl.row_count == 10
    assert (sl.to_pandas()["a"].to_numpy() == df["a"].to_numpy()[10:20]).all()


def test_setitem_column_and_scalar(tbl):
    t, df = tbl
    t["c"] = np.arange(60)
    assert "c" in t.column_names
    assert (t.to_pandas()["c"].to_numpy() == np.arange(60)).all()
    t["d"] = 7
    assert (t.to_pandas()["d"].to_numpy() == 7).all()


def test_setitem_mask(tbl):
    t, df = tbl
    num = t.project(["a"])
    num[num["a"] > 4] = 0
    exp = df[["a"]].mask(df["a"] > 4, 0)
    assert (num.to_pandas()["a"].to_numpy() == exp["a"].to_numpy()).all()


def test_iterrows(tbl):
    t, df = tbl
    rows = list(t.iterrows())
    assert len(rows) == len(df)
    # spot check a handful of rows (order preserved)
    for i in (0, 7, 59):
        idx, row = rows[i]
        assert row["a"] == df["a"].iloc[i]
        assert row["s"] == df["s"].iloc[i]


def test_astype_numeric_to_string(tbl):
    t, df = tbl
    out = t.project(["a"]).astype(str).to_pandas()
    assert (out["a"].to_numpy() == df["a"].astype(str).to_numpy()).all()


def test_astype_string_to_numeric(world_ctx):
    df = pd.DataFrame({"v": ["1", "2", "30", "2"]})
    t = ct.Table.from_pandas(world_ctx, df)
    out = t.astype({"v": np.int64}).to_pandas()
    assert (out["v"].to_numpy() == np.array([1, 2, 30, 2])).all()
    outf = t.astype({"v": np.float32}).to_pandas()
    assert np.allclose(outf["v"].to_numpy(), [1.0, 2.0, 30.0, 2.0])


def test_select_rows_udf(tbl):
    t, df = tbl
    out = t.select_rows(lambda r: r["a"] > 4 and r["s"] != "x")
    exp = df[(df["a"] > 4) & (df["s"] != "x")]
    assert out.row_count == len(exp)
    _sorted_eq(out, exp)


def test_row_cursor(tbl):
    t, _ = tbl
    from cylon_tpu.table import Row

    host = t.to_pydict()
    r = Row(host, 3)
    assert set(r.keys()) == {"a", "b", "s"}
    assert r.row_index == 3
    assert r["a"] == host["a"][3]


def test_join_config_object(local_ctx, rng):
    """JoinConfig object form (reference join_config.hpp:26-189 with static
    builders)."""
    import pandas as pd

    a = pd.DataFrame({"k": rng.integers(0, 10, 50), "x": rng.normal(size=50)})
    b = pd.DataFrame({"k": rng.integers(0, 10, 40), "y": rng.normal(size=40)})
    ta, tb = ct.Table.from_pandas(local_ctx, a), ct.Table.from_pandas(local_ctx, b)
    cfg = ct.JoinConfig.inner_join(on="k", suffixes=("_l", "_r"))
    out = ta.join(tb, config=cfg)
    exp = a.merge(b, on="k", suffixes=("_l", "_r"))
    assert out.row_count == len(exp)
    assert "k_l" in out.column_names and "k_r" in out.column_names
    with pytest.raises(ValueError):
        ct.JoinConfig("inner", algorithm="quantum")
    with pytest.raises(ValueError):
        ct.JoinConfig("sideways")
