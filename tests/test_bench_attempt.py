"""bench.record_tpu_attempt keep-best semantics — this file IS the round's
headline evidence, so its selection rule gets a regression net: best-of-round
at top level, latest verbatim, counts, and the round anchor that keeps a
>12h round from dropping its best mid-round."""
import json
import os

import pytest

import bench


@pytest.fixture
def attempt_env(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "REPO_DIR", str(tmp_path))
    clock = {"now": 1_000_000}
    monkeypatch.setattr(bench.time, "time", lambda: clock["now"])
    path = tmp_path / "benchmarks" / "results" / "BENCH_TPU_attempt.json"

    def capture(vs, rows=8_000_000, at=None, **extra):
        if at is not None:
            clock["now"] = at
        bench.record_tpu_attempt(
            {"platform": "tpu", "vs_baseline": vs, "rows": rows, **extra}
        )
        return json.loads(path.read_text())

    return capture, clock


def test_keep_best_and_latest(attempt_env):
    capture, clock = attempt_env
    out = capture(10.0)
    assert out["vs_baseline"] == 10.0 and out["captures_this_round"] == 1
    clock["now"] += 3600
    out = capture(8.0)  # degraded wake: best stays, latest updates
    assert out["vs_baseline"] == 10.0
    assert out["latest"]["vs_baseline"] == 8.0
    assert out["captures_this_round"] == 2
    clock["now"] += 3600
    out = capture(11.5)  # better wake wins
    assert out["vs_baseline"] == 11.5 and out["captures_this_round"] == 3


def test_round_anchor_not_best_timestamp(attempt_env):
    """A >12h round must keep comparing within the round until the ANCHOR
    ages out — previously freshness tracked the best capture's own
    timestamp, so an 11h-later degraded wake could overwrite the best."""
    capture, clock = attempt_env
    t0 = clock["now"]
    out = capture(10.0)
    assert out["round_started_unix"] == t0
    out = capture(9.0, at=t0 + 11 * 3600)  # within the round: best kept
    assert out["vs_baseline"] == 10.0 and out["captures_this_round"] == 2
    out = capture(7.0, at=t0 + 13 * 3600)  # anchor aged out: NEW round
    assert out["vs_baseline"] == 7.0
    assert out["captures_this_round"] == 1
    assert out["round_started_unix"] == t0 + 13 * 3600


def test_config_change_resets(attempt_env):
    capture, clock = attempt_env
    capture(10.0, rows=8_000_000)
    out = capture(6.0, rows=4_000_000)  # different config: no suppression
    assert out["vs_baseline"] == 6.0 and out["rows"] == 4_000_000


def test_cpu_and_error_lines_never_recorded(attempt_env, tmp_path):
    capture, clock = attempt_env
    results = tmp_path / "benchmarks" / "results"
    bench.record_tpu_attempt({"platform": "cpu", "vs_baseline": 99.0})
    bench.record_tpu_attempt({"platform": "tpu", "error": "x", "vs_baseline": 99.0})
    assert not (results / "BENCH_TPU_attempt.json").exists()


def test_corrupt_previous_file_still_records(attempt_env, tmp_path):
    capture, clock = attempt_env
    results = tmp_path / "benchmarks" / "results"
    results.mkdir(parents=True)
    (results / "BENCH_TPU_attempt.json").write_text("{not json")
    out = capture(9.0)
    assert out["vs_baseline"] == 9.0 and out["captures_this_round"] == 1
