"""Windowed Pallas expand (ops/pallas_gather) semantics on the CPU mesh
(interpret mode), plus end-to-end join equivalence of the windowed emit
path vs the XLA-gather emit path.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cylon_tpu.ops import join as J
from cylon_tpu.ops.pallas_gather import expand_available, expand_rows

pytestmark = pytest.mark.skipif(
    not expand_available(), reason="pallas unavailable"
)


@pytest.mark.parametrize("impl", ["take", "onehot", "take_db", "onehot_db"])
@pytest.mark.parametrize(
    "m,hot,T",
    [(700, 0, 512), (700, 3, 512), (3, 0, 512), (9000, 2, 2048)],
)
def test_expand_rows_oracle(rng, impl, m, hot, T):
    # expand contract: every count >= 1 (zero-count rows are compacted away
    # by the caller — a zero would create a step > 1 and a window miss)
    cnt = rng.integers(1, 4, m)
    if hot:
        cnt[rng.integers(0, m, hot)] = 700  # skewed runs (step 0: safe)
    li = np.repeat(np.arange(m), cnt).astype(np.int32)
    if len(li) == 0:
        li = np.zeros(1, np.int32)
    L = 5
    src = rng.integers(-(2**31), 2**31, (L, m), dtype=np.int64).astype(np.int32)
    got = np.asarray(
        expand_rows(jnp.asarray(src), jnp.asarray(li), T=T, impl=impl,
                    interpret=True)
    )
    want = src[:, np.clip(li, 0, m - 1)]
    assert (got == want).all()


def _emit_pair(rng, how, n_l, n_r, keyspace, with_valid=False, with_f64=False):
    """Run both emit impls on one random probe state; return their outputs."""
    cap_l = max(1 << (n_l - 1).bit_length(), 8)
    cap_r = max(1 << (n_r - 1).bit_length(), 8)
    lk = np.zeros(cap_l, np.int32)
    rk = np.zeros(cap_r, np.int32)
    lk[:n_l] = rng.integers(0, keyspace, n_l)
    rk[:n_r] = rng.integers(0, keyspace, n_r)
    lv = np.zeros(cap_l, np.float32)
    lv[:n_l] = rng.normal(size=n_l)
    rv = np.zeros(cap_r, np.float32)
    rv[:n_r] = rng.normal(size=n_r)
    nl = jnp.int32(n_l)
    nr = jnp.int32(n_r)
    l_key_cols = [(jnp.asarray(lk), None)]
    r_key_cols = [(jnp.asarray(rk), None)]
    l_cols = [(jnp.asarray(lk), None), (jnp.asarray(lv), None)]
    if with_valid:
        lval = np.ones(cap_l, bool)
        lval[: n_l // 2] = rng.random(n_l // 2) > 0.3
        l_cols[1] = (l_cols[1][0], jnp.asarray(lval))
    if with_f64:
        l_cols.append((jnp.asarray(lv.astype(np.float64) * 3), None))
    r_cols = [(jnp.asarray(rk), None), (jnp.asarray(rv), None)]

    howi = J.join_type_id(how)
    lo, cnt, r_order, r_cnt = J.probe_arrays(
        l_key_cols, r_key_cols, nl, nr, cap_l, cap_r, howi
    )
    total = int(J.count_from_probe(cnt, r_cnt, nl, nr, howi))
    cap_out = max(1 << (max(total, 1) - 1).bit_length(), 8)
    from cylon_tpu.ops.gather import pack_gather

    r_sorted, _ = pack_gather(r_cols, r_order)
    r_sorted = [
        (d, None) for (d, v) in r_sorted
    ]  # r_cols mask-free: keep mask-free
    outs = {}
    for impl in ("gather", "windowed_interp"):
        cols, n_out = J._emit_inner_left(
            lo, cnt, l_cols, r_sorted, nl, howi, cap_out, cap_r, impl
        )
        outs[impl] = (
            [(np.asarray(d), None if v is None else np.asarray(v)) for d, v in cols],
            int(n_out),
        )
    return outs, total


def _rows(cols, n):
    """Set-comparable row tuples (validity-aware)."""
    out = []
    for i in range(n):
        row = []
        for d, v in cols:
            ok = True if v is None else bool(v[i])
            row.append(None if not ok else d[i].item())
        out.append(tuple(row))
    return sorted(out, key=repr)


@pytest.mark.parametrize("how", ["inner", "left"])
@pytest.mark.parametrize("n_l,n_r,keyspace", [(300, 200, 40), (64, 64, 5), (5, 300, 3)])
def test_windowed_emit_matches_gather_emit(rng, how, n_l, n_r, keyspace):
    outs, total = _emit_pair(rng, how, n_l, n_r, keyspace)
    (a_cols, a_n), (b_cols, b_n) = outs.values()
    assert a_n == b_n == total
    assert _rows(a_cols, a_n) == _rows(b_cols, b_n)


def test_windowed_emit_validity_and_f64(rng):
    outs, total = _emit_pair(
        rng, "left", 200, 150, 30, with_valid=True, with_f64=True
    )
    (a_cols, a_n), (b_cols, b_n) = outs.values()
    assert a_n == b_n == total
    assert _rows(a_cols, a_n) == _rows(b_cols, b_n)


def test_windowed_emit_wide_table_gate(rng, monkeypatch):
    """Tables wide enough to overflow the expand's VMEM must silently take
    the XLA gather path (the windowed kernel must not even be invoked)."""
    import cylon_tpu.ops.pallas_gather as pg

    def boom(*a, **k):  # pragma: no cover - must not be reached
        raise AssertionError("expand_rows called despite the VMEM gate")

    monkeypatch.setattr(pg, "expand_rows_raw", boom)
    n, cap = 40, 64
    lk = np.zeros(cap, np.int32)
    lk[:n] = rng.integers(0, 10, n)
    rk = lk.copy()
    # 110 int64 columns -> 220 data lanes + bookkeeping > the 200-lane gate
    l_cols = [(jnp.asarray(lk), None)] + [
        (jnp.asarray(np.arange(cap, dtype=np.int64)), None) for _ in range(110)
    ]
    lo, cnt, r_order, r_cnt = J.probe_arrays(
        [(jnp.asarray(lk), None)], [(jnp.asarray(rk), None)],
        jnp.int32(n), jnp.int32(n), cap, cap, J.INNER,
    )
    from cylon_tpu.ops.gather import pack_gather

    r_sorted, _ = pack_gather([(jnp.asarray(rk), None)], r_order)
    cols, n_out = J._emit_inner_left(
        lo, cnt, l_cols, [(r_sorted[0][0], None)],
        jnp.int32(n), J.INNER, 256, cap, "windowed_interp",
    )
    assert int(n_out) > 0  # produced via the gather path, kernel untouched


def test_windowed_emit_empty_left(rng):
    outs, total = _emit_pair(rng, "inner", 0, 50, 5)
    (a_cols, a_n), (b_cols, b_n) = outs.values()
    assert a_n == b_n == total == 0


@pytest.mark.parametrize("force_sm", [False, True])
def test_windowed_emit_multidevice_shard_map(ctx8, rng, monkeypatch, force_sm):
    """The windowed emit per-shard inside jit(shard_map) on a multi-device
    mesh (VERDICT r4 item 3's correctness gate), plus the forced-shard_map
    knob the hardware probe uses. Compares the full distributed join
    against pandas."""
    import pandas as pd

    import cylon_tpu as ct

    monkeypatch.setenv("CYLON_TPU_EMIT_IMPL", "windowed")
    if force_sm:
        monkeypatch.setenv("CYLON_TPU_FORCE_SHARD_MAP", "1")
    n = 300
    ldf = pd.DataFrame({
        "k": rng.integers(0, 40, n).astype(np.int32),
        "v": rng.normal(size=n).astype(np.float32),
    })
    rdf = pd.DataFrame({
        "k": rng.integers(0, 40, n).astype(np.int32),
        "w": rng.normal(size=n).astype(np.float32),
    })
    left = ct.Table.from_pydict(ctx8, {c: ldf[c].values for c in ldf})
    right = ct.Table.from_pydict(ctx8, {c: rdf[c].values for c in rdf})
    got = left.distributed_join(right, on="k", how="left").to_pandas()
    want = ldf.merge(rdf, on="k", how="left")
    want = want.assign(k_x=want["k"], k_y=want["k"]).drop(columns=["k"])
    # left-join null k_y: table semantics keep k_y null only for unmatched
    want.loc[want["w"].isna(), "k_y"] = np.nan
    cols = sorted(got.columns)
    g = got[cols].sort_values(cols, kind="mergesort").reset_index(drop=True)
    w = want[cols].sort_values(cols, kind="mergesort").reset_index(drop=True)
    pd.testing.assert_frame_equal(g, w, check_dtype=False, atol=1e-6)
