"""Test harness: virtual 8-device CPU mesh.

Reference analog: CTest runs every suite under ``mpirun -np {1,2,4}``
(cpp/test/CMakeLists.txt:44-117). Here a single process gets 8 virtual XLA CPU
devices (SURVEY.md §4.3) and the same tests run on 1-, 2-, 4- and 8-device
meshes via the ``ctx`` fixtures.
"""
import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

import cylon_tpu as ct


@pytest.fixture(scope="session")
def devices():
    d = jax.devices()
    assert len(d) >= 8, f"need 8 virtual CPU devices, got {len(d)}"
    return d


@pytest.fixture(scope="session")
def local_ctx(devices):
    return ct.CylonContext.init()


@pytest.fixture(scope="session", params=[1, 2, 4, 8])
def world_ctx(request, devices):
    """Mesh sizes mirroring the reference's mpirun -np sweep (+8)."""
    n = request.param
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:n]))


@pytest.fixture(scope="session")
def ctx8(devices):
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:8]))


@pytest.fixture
def rng():
    return np.random.default_rng(42)
