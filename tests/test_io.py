"""IO tests: native CSV codec + read_csv/write_csv/parquet round-trips.

Reference analog: the reference reads per-rank CSVs in every distributed test
(cpp/test/join_test.cpp:21-24) and round-trips via WriteCSV
(table.cpp:244-253); io options builders io/csv_read_config.hpp.
"""
import os

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu import native
from cylon_tpu.io import CSVReadOptions, CSVWriteOptions, read_csv, write_csv
from cylon_tpu.io.parquet import read_parquet, write_parquet


def _mixed_df(n, rng, with_nulls=True):
    df = pd.DataFrame(
        {
            "i": rng.integers(-1000, 1000, n),
            "f": rng.normal(size=n),
            "s": np.array(["alpha", "beta", "gamma", "a,b", 'q"x'])[
                rng.integers(0, 5, n)
            ],
            "b": rng.integers(0, 2, n).astype(bool),
        }
    )
    if with_nulls:
        df.loc[df.index[:: max(n // 7, 1)], "f"] = np.nan
    return df


def test_native_available():
    assert native.available(), "native codec should build in this image"


def test_native_read_matches_pandas(tmp_path, rng):
    df = _mixed_df(500, rng)
    p = str(tmp_path / "t.csv")
    df.to_csv(p, index=False)
    cols = native.read_csv(p)
    by_name = {c.name: c for c in cols}
    assert (by_name["i"].data == df["i"].to_numpy()).all()
    f = by_name["f"]
    fv = df["f"].to_numpy()
    mask = ~np.isnan(fv)
    assert np.allclose(f.data[mask], fv[mask])
    assert f.valid is not None and (f.valid == mask).all()
    s = by_name["s"]
    assert (s.dictionary[s.data] == df["s"].to_numpy()).all()
    assert list(s.dictionary) == sorted(s.dictionary)  # sorted-dict invariant
    assert (by_name["b"].data == df["b"].to_numpy()).all()


def test_read_csv_roundtrip_local(tmp_path, local_ctx, rng):
    df = _mixed_df(200, rng)
    p = str(tmp_path / "t.csv")
    df.to_csv(p, index=False)
    t = read_csv(local_ctx, p)
    assert t.row_count == 200
    back = t.to_pandas()
    pd.testing.assert_frame_equal(back, df, check_dtype=False)


def test_write_csv_roundtrip(tmp_path, local_ctx, rng):
    df = _mixed_df(150, rng)
    t = ct.Table.from_pandas(local_ctx, df)
    p = str(tmp_path / "out.csv")
    write_csv(t, p)
    t2 = read_csv(local_ctx, p)
    pd.testing.assert_frame_equal(t2.to_pandas(), df, check_dtype=False)


def test_read_csv_per_shard_files(tmp_path, ctx8, rng):
    """world_size files -> file i lands on shard i; string dictionaries are
    unified across files (reference per-rank csv1_{RANK}.csv pattern)."""
    frames = []
    for i in range(8):
        df = pd.DataFrame(
            {
                "k": rng.integers(0, 50, 30 + i),
                # disjoint-ish string sets to force dict unification
                "s": np.array([f"s{i}a", f"s{i}b", "shared"])[rng.integers(0, 3, 30 + i)],
            }
        )
        p = str(tmp_path / f"part_{i}.csv")
        df.to_csv(p, index=False)
        frames.append(df)
    t = read_csv(ctx8, [str(tmp_path / f"part_{i}.csv") for i in range(8)])
    assert list(t.row_counts) == [len(f) for f in frames]
    expect = pd.concat(frames, ignore_index=True)
    pd.testing.assert_frame_equal(t.to_pandas(), expect, check_dtype=False)


def test_read_options(tmp_path, local_ctx):
    p = str(tmp_path / "t.csv")
    with open(p, "w") as f:
        f.write("1;2.5\n3;4.5\n")
    opts = CSVReadOptions().with_delimiter(";").with_column_names(["x", "y"])
    t = read_csv(local_ctx, p, opts)
    assert t.column_names == ["x", "y"]
    assert list(t.to_pydict()["x"]) == [1, 3]
    w = CSVWriteOptions().with_delimiter("|")
    out = str(tmp_path / "o.csv")
    write_csv(t, out, w)
    assert open(out).read().splitlines()[0] == "x|y"


def test_nulls_roundtrip(tmp_path, local_ctx):
    p = str(tmp_path / "t.csv")
    with open(p, "w") as f:
        f.write("a,b,s\n1,,x\n,2.5,\n3,1.5,z\n")
    t = read_csv(local_ctx, p)
    d = t.to_pydict()
    assert np.isnan(d["a"][1]) and d["a"][0] == 1
    assert np.isnan(d["b"][0])
    assert d["s"][1] is None and d["s"][2] == "z"
    out = str(tmp_path / "o.csv")
    write_csv(t, out)
    t2 = read_csv(local_ctx, out)
    pd.testing.assert_frame_equal(t2.to_pandas(), t.to_pandas(), check_dtype=False)


def test_pyarrow_fallback_matches_native(tmp_path, local_ctx, rng, monkeypatch):
    df = _mixed_df(100, rng, with_nulls=False)
    p = str(tmp_path / "t.csv")
    df.to_csv(p, index=False)
    t_native = read_csv(local_ctx, p)
    monkeypatch.setattr(native, "available", lambda: False)
    t_pa = read_csv(local_ctx, p)
    pd.testing.assert_frame_equal(
        t_native.to_pandas(), t_pa.to_pandas(), check_dtype=False
    )


def test_parquet_roundtrip(tmp_path, local_ctx, rng):
    df = _mixed_df(120, rng, with_nulls=False)
    t = ct.Table.from_pandas(local_ctx, df)
    p = str(tmp_path / "t.parquet")
    write_parquet(t, p)
    t2 = read_parquet(local_ctx, p)
    pd.testing.assert_frame_equal(t2.to_pandas(), df, check_dtype=False)


def test_distributed_csv_join_e2e(tmp_path, ctx8, rng):
    """End-to-end: per-shard CSVs -> distributed join -> pandas oracle."""
    lf, rf = [], []
    for i in range(8):
        l = pd.DataFrame({"k": rng.integers(0, 40, 25), "v": rng.normal(size=25)})
        r = pd.DataFrame({"k": rng.integers(0, 40, 20), "w": rng.normal(size=20)})
        l.to_csv(str(tmp_path / f"l_{i}.csv"), index=False)
        r.to_csv(str(tmp_path / f"r_{i}.csv"), index=False)
        lf.append(l)
        rf.append(r)
    lt = read_csv(ctx8, [str(tmp_path / f"l_{i}.csv") for i in range(8)])
    rt = read_csv(ctx8, [str(tmp_path / f"r_{i}.csv") for i in range(8)])
    out = lt.distributed_join(rt, on="k", how="inner").to_pandas()
    # cylon keeps both key columns with suffixes (join_utils.cpp:28-160)
    assert (out["k_x"] == out["k_y"]).all()
    out = out.rename(columns={"k_x": "k"}).drop(columns=["k_y"])
    expect = pd.concat(lf).merge(pd.concat(rf), on="k", how="inner")
    assert len(out) == len(expect)
    cols = list(out.columns)
    a = out.sort_values(cols).reset_index(drop=True)
    b = expect[cols].sort_values(cols).reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b, check_dtype=False)


def test_multifile_heterogeneous_types(tmp_path, ctx8):
    """Per-file type inference disagreement promotes to a common type instead
    of concatenating dictionary codes as integers."""
    # int-inferred file + string-inferred file for the same column
    (tmp_path / "a.csv").write_text("k,v\n1,10\n3,30\n")
    (tmp_path / "b.csv").write_text("k,v\nfoo,1.5\nbar,2.5\n")
    paths = [str(tmp_path / "a.csv"), str(tmp_path / "b.csv")]
    t = read_csv(ctx8, paths)
    k = list(t.to_pydict()["k"])
    assert k == ["1", "3", "bar", "foo"] or k == ["1", "3", "foo", "bar"], k
    v = np.asarray(t.to_pydict()["v"], np.float64)
    assert np.allclose(v, [10.0, 30.0, 1.5, 2.5])
    # reversed order must not crash either
    t2 = read_csv(ctx8, paths[::-1])
    assert t2.row_count == 4
