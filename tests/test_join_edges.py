"""Join edge cases: fast-path sentinels, NaN semantics, x64-off mode."""
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct


def test_int32_max_keys(ctx8):
    """Live keys equal to INT32_MAX canonicalize to the padding sentinel —
    the probe's count correction must keep them exact."""
    lmax = np.int32(2**31 - 1)
    l = pd.DataFrame({"k": np.array([lmax, 0, 5, lmax, 7], np.int32),
                      "x": np.arange(5.0)})
    r = pd.DataFrame({"k": np.array([lmax, 5, lmax, lmax, 2], np.int32),
                      "y": np.arange(5.0) * 10})
    tl = ct.Table.from_pandas(ctx8, l)
    tr = ct.Table.from_pandas(ctx8, r)
    for how in ["inner", "left", "right", "outer"]:
        got = tl.distributed_join(tr, on="k", how=how)
        exp = l.merge(r, on="k", how=how)
        assert got.row_count == len(exp), (how, got.row_count, len(exp))
    # value check for inner
    got = tl.distributed_join(tr, on="k", how="inner").to_pandas()
    exp = l.merge(r, on="k", how="inner")
    assert sorted(got["x"].tolist()) == sorted(exp["x"].tolist())
    assert sorted(got["y"].tolist()) == sorted(exp["y"].tolist())


def test_nan_keys_match_like_pandas(ctx8):
    """pandas.merge matches NaN keys to NaN (and never to 0.0)."""
    l = pd.DataFrame({"k": np.array([np.nan, 0.0, 1.5], np.float64),
                      "x": [1.0, 2.0, 3.0]})
    r = pd.DataFrame({"k": np.array([np.nan, 0.0, 2.5], np.float64),
                      "y": [10.0, 20.0, 30.0]})
    tl = ct.Table.from_pandas(ctx8, l)
    tr = ct.Table.from_pandas(ctx8, r)
    got = tl.distributed_join(tr, on="k", how="inner").to_pandas()
    exp = l.merge(r, on="k", how="inner")
    assert got.shape[0] == exp.shape[0]
    assert sorted(got["x"].tolist()) == sorted(exp["x"].tolist())


def test_multi_key_join(ctx8, rng):
    l = pd.DataFrame({
        "a": rng.integers(0, 5, 40),
        "b": rng.integers(0, 4, 40),
        "x": rng.normal(size=40),
    })
    r = pd.DataFrame({
        "a": rng.integers(0, 5, 35),
        "b": rng.integers(0, 4, 35),
        "y": rng.normal(size=35),
    })
    tl = ct.Table.from_pandas(ctx8, l)
    tr = ct.Table.from_pandas(ctx8, r)
    for how in ["inner", "left", "outer"]:
        got = tl.distributed_join(tr, on=["a", "b"], how=how)
        exp = l.merge(r, on=["a", "b"], how=how)
        assert got.row_count == len(exp), how


def test_left_on_right_on(ctx8, rng):
    l = pd.DataFrame({"ka": rng.integers(0, 10, 30), "x": rng.normal(size=30)})
    r = pd.DataFrame({"kb": rng.integers(0, 10, 25), "y": rng.normal(size=25)})
    tl = ct.Table.from_pandas(ctx8, l)
    tr = ct.Table.from_pandas(ctx8, r)
    got = tl.distributed_join(tr, left_on=["ka"], right_on=["kb"], how="inner")
    exp = l.merge(r, left_on="ka", right_on="kb", how="inner")
    assert got.row_count == len(exp)
    assert got.column_names == ["ka", "x", "kb", "y"]


NO_X64_SCRIPT = r"""
import os
os.environ["CYLON_TPU_NO_X64"] = "1"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np, pandas as pd
import cylon_tpu as ct
rng = np.random.default_rng(0)
l = pd.DataFrame({"k": rng.integers(0, 50, 300).astype(np.int32),
                  "x": rng.normal(size=300).astype(np.float32)})
r = pd.DataFrame({"k": rng.integers(0, 50, 200).astype(np.int32),
                  "y": rng.normal(size=200).astype(np.float32)})
ctx = ct.CylonContext.init_distributed(ct.TPUConfig())
tl = ct.Table.from_pandas(ctx, l); tr = ct.Table.from_pandas(ctx, r)
got = tl.distributed_join(tr, on="k", how="inner")
exp = l.merge(r, on="k", how="inner")
assert got.row_count == len(exp), (got.row_count, len(exp))
gs = np.sort(got.to_pandas()["x"].to_numpy()); es = np.sort(exp["x"].to_numpy())
assert np.allclose(gs, es)
print("NO_X64_JOIN_OK", got.row_count)
"""


def test_join_without_x64():
    """The benchmark config: x64 disabled, int32 keys — the fast path must
    not rely on int64 existing (regression for the live-bit packing bug)."""
    out = subprocess.run(
        [sys.executable, "-c", NO_X64_SCRIPT],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "NO_X64_JOIN_OK" in out.stdout


def test_mixed_dtype_keys(ctx8):
    """int32 vs uint32 keys must promote before canonicalization."""
    l = pd.DataFrame({"k": np.array([1, 2, 3, 5], np.int32), "x": [1.0, 2.0, 3.0, 4.0]})
    r = pd.DataFrame({"k": np.array([1, 2, 3, 4], np.uint32), "y": [1.0, 2.0, 3.0, 4.0]})
    tl = ct.Table.from_pandas(ctx8, l)
    tr = ct.Table.from_pandas(ctx8, r)
    got = tl.distributed_join(tr, on="k", how="inner")
    assert got.row_count == 3
    # int32 min vs uint32 0 must NOT match
    l2 = pd.DataFrame({"k": np.array([-(2**31)], np.int32), "x": [1.0]})
    r2 = pd.DataFrame({"k": np.array([0], np.uint32), "y": [1.0]})
    got2 = ct.Table.from_pandas(ctx8, l2).distributed_join(
        ct.Table.from_pandas(ctx8, r2), on="k", how="inner"
    )
    assert got2.row_count == 0


def test_f32_zero_sign_distributed(ctx8):
    """-0.0 and +0.0 float32 keys must match across the shuffle (hash lane
    canonicalization, ops/hash.py f32 branch)."""
    import pandas as pd

    l = {"k": np.array([-0.0, 1.0], np.float32), "v": np.array([1, 2], np.int32)}
    r = {"k": np.array([0.0, 2.0], np.float32), "w": np.array([3, 4], np.int32)}
    lt = ct.Table.from_pydict(ctx8, l)
    rt = ct.Table.from_pydict(ctx8, r)
    out = lt.distributed_join(rt, on="k", how="inner")
    expect = pd.DataFrame(l).merge(pd.DataFrame(r), on="k")
    assert out.row_count == len(expect) == 1


def test_mixed_width_int_keys_distributed(ctx8, rng):
    """int32 vs int64 keys promote BEFORE the shuffle so equal values hash to
    the same shard (table.py _promote_key_pair)."""
    import pandas as pd

    kl = rng.integers(0, 100, 300).astype(np.int32)
    kr = rng.integers(0, 100, 200).astype(np.int64)
    lt = ct.Table.from_pydict(ctx8, {"k": kl, "v": rng.normal(size=300)})
    rt = ct.Table.from_pydict(ctx8, {"k": kr, "w": rng.normal(size=200)})
    out = lt.distributed_join(rt, on="k", how="inner")
    expect = pd.DataFrame({"k": kl.astype(np.int64)}).merge(
        pd.DataFrame({"k": kr}), on="k"
    )
    assert out.row_count == len(expect)


def test_mixed_sign_promotion_requires_x64(ctx8):
    """int32 x uint32 promotes to int64; with x64 disabled that must raise
    (silent wrap would fabricate matches, e.g. 2**31 == -2**31)."""
    from cylon_tpu.compat import enable_x64

    lt = ct.Table.from_pydict(ctx8, {"k": np.array([-(2**31)], np.int32)})
    rt = ct.Table.from_pydict(ctx8, {"k": np.array([2**31], np.uint32)})
    with enable_x64(False):
        with pytest.raises(ValueError, match="64-bit"):
            lt.join(rt, on="k", how="inner")


def test_speculative_overflow_falls_back(world_ctx, rng):
    """Join output larger than the speculative cap (cap_l+cap_r): the
    single-dispatch path must detect overflow and rerun the exact two-phase
    count->emit (table.py Table.join speculative block)."""
    import pandas as pd

    # 64 rows per side, all the same key -> 4096 output rows >> 64+64
    k = np.zeros(64, np.int32)
    lt = ct.Table.from_pydict(world_ctx, {"k": k, "v": np.arange(64, dtype=np.int32)})
    rt = ct.Table.from_pydict(world_ctx, {"k": k, "w": np.arange(64, dtype=np.int32)})
    out = lt.join(rt, on="k", how="inner")
    assert out.row_counts.sum() == sum(
        int(n) * int(m) for n, m in zip(lt.row_counts, rt.row_counts)
    )
    dout = lt.distributed_join(rt, on="k", how="inner")
    assert dout.row_counts.sum() == 64 * 64
    expect = pd.DataFrame({"k": k, "v": np.arange(64)}).merge(
        pd.DataFrame({"k": k, "w": np.arange(64)}), on="k"
    )
    got = (
        dout.to_pandas()[["k_x", "v", "w"]]
        .rename(columns={"k_x": "k"})
        .sort_values(["v", "w"])
        .reset_index(drop=True)
    )
    pd.testing.assert_frame_equal(
        got, expect.sort_values(["v", "w"]).reset_index(drop=True), check_dtype=False
    )


def test_fused_overflow_retry_on_mesh(world_ctx, rng):
    """Fused mode with undersized capacities on a mesh: the overflow lane
    must trigger the capacity-doubling retry (table.py _fused_join loop) and
    the retried result must match pandas. Extreme skew (every row the same
    key) lands the whole join on ONE shard, so the initial join_cap of
    2*(1+respill)*world*bucket_cap is guaranteed too small."""
    n = 64
    k = np.zeros(n, np.int32)
    lt = ct.Table.from_pydict(
        world_ctx, {"k": k, "v": np.arange(n, dtype=np.int32)}
    )
    rt = ct.Table.from_pydict(
        world_ctx, {"k": k, "w": np.arange(n, dtype=np.int32)}
    )
    out = lt.distributed_join(rt, on="k", how="inner", mode="fused")
    assert out.row_counts.sum() == n * n
    expect = (
        pd.DataFrame({"k": k, "v": np.arange(n)})
        .merge(pd.DataFrame({"k": k, "w": np.arange(n)}), on="k")
        .sort_values(["v", "w"])
        .reset_index(drop=True)
    )
    got = (
        out.to_pandas()[["k_x", "v", "w"]]
        .rename(columns={"k_x": "k"})
        .sort_values(["v", "w"])
        .reset_index(drop=True)
    )
    pd.testing.assert_frame_equal(got, expect, check_dtype=False)


def test_join_compacts_tiny_output(ctx8, rng):
    """A selective join output is compacted below the speculative cap."""
    n = 3000
    lt = ct.Table.from_pydict(
        ctx8, {"k": np.arange(n, dtype=np.int32), "v": rng.normal(size=n)}
    )
    rt = ct.Table.from_pydict(
        ctx8, {"k": np.array([7], np.int32), "w": np.array([1.0], np.float32)}
    )
    out = lt.distributed_join(rt, on="k", how="inner")
    assert out.row_count == 1
    assert out.shard_cap <= 64  # not the speculative cap_l+cap_r


def test_local_string_vs_numeric_key_raises(local_ctx):
    """Mixed string/numeric key pairs are rejected in the LOCAL join too —
    otherwise dictionary codes would compare against numeric values
    (table.py _unify_dict_pair guard)."""
    lt = ct.Table.from_pydict(local_ctx, {"k": ["a", "b", "c"]})
    rt = ct.Table.from_pydict(local_ctx, {"k": np.array([0, 1, 9], np.int32)})
    with pytest.raises(ValueError, match="string key"):
        lt.join(rt, on="k", how="inner")


def test_join_count_int32_wrap_raises(local_ctx):
    """65536 x 65536 rows on one key = 2^32 matches: the int32 count wraps to
    0, the float32 shadow catches it (ops/join.py count_overflow_check)."""
    n = 65536
    k = np.zeros(n, np.int32)
    lt = ct.Table.from_pydict(local_ctx, {"k": k})
    rt = ct.Table.from_pydict(local_ctx, {"k": k})
    with pytest.raises(ValueError, match="2\\^31"):
        lt.join(rt, on="k", how="inner")
