"""Pipeline (sorted-run) groupby vs pandas and vs the hash groupby.

Reference analog: groupby/pipeline_groupby.cpp + DistributedPipelineGroupBy
(groupby/groupby.cpp:93-137).
"""
import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct


@pytest.fixture
def data(rng):
    return pd.DataFrame({
        "k": rng.integers(0, 15, 120),
        "v": rng.normal(size=120),
        "w": rng.integers(0, 100, 120),
    })


def test_pipeline_groupby_matches_hash(local_ctx, data):
    t = ct.Table.from_pandas(local_ctx, data).sort("k")
    a = t.pipeline_groupby("k", {"v": "sum", "w": "max"}).to_pandas()
    b = t.groupby("k", {"v": "sum", "w": "max"}).to_pandas()
    pd.testing.assert_frame_equal(
        a.sort_values("k").reset_index(drop=True),
        b.sort_values("k").reset_index(drop=True),
    )
    exp = data.groupby("k").agg(v_sum=("v", "sum"), w_max=("w", "max")).reset_index()
    got = a.sort_values("k").reset_index(drop=True)
    assert np.allclose(got["v_sum"], exp["v_sum"])
    assert (got["w_max"].to_numpy() == exp["w_max"].to_numpy()).all()


def test_distributed_pipeline_groupby(world_ctx, data):
    t = ct.Table.from_pandas(world_ctx, data)
    out = t.distributed_pipeline_groupby("k", {"v": "mean"})
    got = out.to_pandas().sort_values("k").reset_index(drop=True)
    exp = data.groupby("k")["v"].mean().reset_index().rename(columns={"v": "v_mean"})
    assert np.allclose(got["v_mean"].to_numpy(), exp["v_mean"].to_numpy())
    assert (got["k"].to_numpy() == exp["k"].to_numpy()).all()


def test_pipeline_groupby_multikey(local_ctx, rng):
    df = pd.DataFrame({
        "a": rng.integers(0, 5, 60),
        "b": rng.integers(0, 4, 60),
        "v": rng.normal(size=60),
    })
    t = ct.Table.from_pandas(local_ctx, df).sort(["a", "b"])
    got = t.pipeline_groupby(["a", "b"], {"v": "count"}).to_pandas()
    exp = df.groupby(["a", "b"])["v"].count().reset_index()
    assert len(got) == len(exp)
    got = got.sort_values(["a", "b"]).reset_index(drop=True)
    assert (got["v_count"].to_numpy() == exp["v"].to_numpy()).all()


def test_pipeline_groupby_with_nulls(local_ctx):
    df = pd.DataFrame({"k": [1, 1, 2, 2, 3], "v": [1.0, np.nan, 2.0, 4.0, np.nan]})
    t = ct.Table.from_pandas(local_ctx, df).sort("k")
    got = t.pipeline_groupby("k", {"v": "sum"}).to_pandas().sort_values("k")
    # Arrow semantics (like the reference): sum of an all-null group is null
    # (pandas would give 0.0); non-null groups skip nulls
    vals = got["v_sum"].to_numpy()
    assert np.allclose(vals[:2], [1.0, 6.0])
    assert np.isnan(vals[2])
