"""Feedback autopilot (ISSUE 11): store durability + tuning correctness.

Covers the acceptance checklist:
- journal round-trip, torn-tail-line recovery, compaction bound;
- the CYLON_TPU_NO_AUTOTUNE differential oracle (identical results on
  every shape, warm or cold store);
- the hysteresis no-flap pin (alternating observations must not
  oscillate recompiles — asserted via the plan-cache miss counter);
- tuned-decision-in-fingerprint pin (a flip re-keys the plan exactly
  once; the kill switch re-keys like the other gates);
- explain(analyze=True) ``tuned:`` annotation golden;
- bounded in-process histogram registry with store flush on eviction.
"""
import json
import os

import numpy as np
import pytest

import cylon_tpu as ct
from cylon_tpu.obs import metrics as obs_metrics
from cylon_tpu.obs import store as obs_store
from cylon_tpu.plan import feedback as fb
from cylon_tpu.plan.lazy import gated_fingerprint
from cylon_tpu.utils import tracing


@pytest.fixture
def obs_env(tmp_path, monkeypatch):
    """A fresh observation store + fast hysteresis for the test."""
    d = str(tmp_path / "obs")
    monkeypatch.setenv("CYLON_TPU_OBS_DIR", d)
    monkeypatch.setenv("CYLON_TPU_AUTOTUNE_MIN_OBS", "2")
    obs_store.reset_stores()
    yield d
    obs_store.reset_stores()


@pytest.fixture(scope="module")
def ctx4(devices):
    # module-scoped: the tests share one mesh's jit caches (each test's
    # plans use distinct value-column names, so plan fingerprints — and
    # their per-tmpdir store profiles — never collide across tests)
    return ct.CylonContext.init_distributed(
        ct.TPUConfig(devices=devices[:4])
    )


def _pair(ctx, rng, n, sel, vname="v"):
    keyspace = max(n // 6, 8)
    lk = rng.integers(0, keyspace, n).astype(np.int32)
    rk = rng.integers(0, keyspace, max(n // 2, 8)).astype(np.int32)
    rk = np.where(
        rng.random(len(rk)) >= sel, rk + 10 * keyspace, rk
    ).astype(np.int32)
    lt = ct.Table.from_pydict(
        ctx, {"k": lk, vname: rng.random(n).astype(np.float32)}
    )
    rt = ct.Table.from_pydict(
        ctx, {"rk": rk, "w": rng.random(len(rk)).astype(np.float32)}
    )
    return lt, rt


def _plan(lt, rt, vname="v"):
    return lt.lazy().join(
        rt.lazy(), left_on="k", right_on="rk", how="inner"
    ).groupby("k", {vname: "sum"})


# ----------------------------------------------------------------------
# store durability
# ----------------------------------------------------------------------
def test_journal_round_trip(tmp_path):
    d = str(tmp_path / "s")
    s = obs_store.ObsStore(d)
    for i in range(10):
        s.record({"k": "exec", "fp": "aaaa", "world": 4, "row_bytes": 8,
                  "hot": 100 + i, "coll": 1000})
        s.record({"k": "lat", "fp": "aaaa", "s": 0.01 * (i + 1)})
    s.close()
    s2 = obs_store.ObsStore(d)
    p = s2.profiles["aaaa"]
    assert p["n"] == 10
    assert p["hot"] == 109
    assert p["lat"]["n"] == 10
    assert p["coll_sum"] == 10_000
    assert s2.skipped_lines == 0
    # quantiles read back off the merged buckets
    q = obs_store.lat_quantile(p["lat"], 0.5)
    assert 0.01 <= q <= 0.11


def test_torn_tail_line_recovery(tmp_path):
    d = str(tmp_path / "s")
    s = obs_store.ObsStore(d)
    for i in range(5):
        s.record({"k": "exec", "fp": "bbbb", "world": 2, "row_bytes": 4,
                  "hot": 50})
    s.close()
    # simulate a crash mid-append: a torn half-record at the tail AND a
    # garbage line in the middle must both be skipped, everything else
    # kept
    with open(os.path.join(d, "journal.jsonl"), "a") as f:
        f.write('{"k": "exec", "fp": "bbbb", "wor')
    s2 = obs_store.ObsStore(d)
    assert s2.profiles["bbbb"]["n"] == 5
    assert s2.skipped_lines == 1
    # and the reloaded store keeps accepting records
    s2.record({"k": "exec", "fp": "bbbb", "world": 2, "row_bytes": 4,
               "hot": 50})
    assert s2.profiles["bbbb"]["n"] == 6
    s2.close()


def test_compaction_bounds_journal(tmp_path):
    d = str(tmp_path / "s")
    s = obs_store.ObsStore(d, compact_every=16)
    for i in range(100):
        s.record({"k": "lat", "fp": f"fp{i % 3}", "s": 0.001})
    # the journal folded into snapshot.json on every 16th record: the
    # live journal holds fewer than compact_every lines and the
    # snapshot carries the rest
    with open(s.journal_path) as f:
        assert sum(1 for _ in f) < 16
    with open(s.snapshot_path) as f:
        snap = json.load(f)
    assert set(snap["profiles"]) == {"fp0", "fp1", "fp2"}
    total = sum(p["lat"]["n"] for p in s.profiles.values())
    assert total == 100
    s.close()
    # nothing lost across the reload either
    s2 = obs_store.ObsStore(d)
    assert sum(p["lat"]["n"] for p in s2.profiles.values()) == 100
    s2.close()


def test_compaction_crash_window_never_double_absorbs(tmp_path):
    """A crash between compact()'s snapshot rename and its journal
    truncate leaves the folded records in BOTH files; the snapshot's
    per-writer jseq high-water mark must dedup them on load."""
    d = str(tmp_path / "s")
    s = obs_store.ObsStore(d, compact_every=10 ** 9)
    journal_path = s.journal_path  # this writer's own journal
    recs = []
    for i in range(6):
        r = {"k": "exec", "fp": "cc", "world": 4, "row_bytes": 8,
             "hot": 10}
        s.record(r)  # record() stamps the journal id onto the dict
        recs.append(r)
    s.compact()  # own journal truncated, snapshot carries jseqs[pid]=6
    s.close()
    # simulate the crash window: the folded records are still in the
    # writer's journal when the process dies
    with open(journal_path, "a") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    s2 = obs_store.ObsStore(d)
    assert s2.profiles["cc"]["n"] == 6, "folded records double-absorbed"
    # and genuinely-new records after the stale tail still absorb
    s2.record({"k": "exec", "fp": "cc", "world": 4, "row_bytes": 8,
               "hot": 10})
    assert s2.profiles["cc"]["n"] == 7
    s2.close()


def test_profile_cap_evicts_lru(tmp_path, monkeypatch):
    d = str(tmp_path / "s")
    monkeypatch.setattr(obs_store, "PROFILE_CAP", 8)
    s = obs_store.ObsStore(d, compact_every=10 ** 9)
    for i in range(20):
        s.record({"k": "lat", "fp": f"fp{i}", "s": 0.001})
    s.compact()
    assert len(s.profiles) <= 8
    # the most recent fingerprints survive
    assert "fp19" in s.profiles and "fp0" not in s.profiles
    s.close()


# ----------------------------------------------------------------------
# differential oracle + fingerprint discipline
# ----------------------------------------------------------------------
def test_no_autotune_oracle_exact(ctx4, rng, obs_env):
    """Warm-store tuned execution returns bit-identical results to the
    CYLON_TPU_NO_AUTOTUNE=1 static-heuristic run on join/groupby/sort
    shapes at several selectivities."""
    for sel, vname in ((0.1, "a"), (1.0, "b")):
        lt, rt = _pair(ctx4, rng, 3000, sel, vname)
        lf = _plan(lt, rt, vname)
        with fb.autotune_disabled():
            want = lf.collect().to_pandas()
        for _ in range(4):  # explore -> decide -> tuned
            got = lf.collect().to_pandas()
            assert got.equals(want)
        srt = lt.lazy().sort("k")
        with fb.autotune_disabled():
            want_s = srt.collect().to_pandas()
        assert srt.collect().to_pandas().equals(want_s)


def test_kill_switch_rekeys_fingerprint(ctx4, rng, obs_env):
    lt, rt = _pair(ctx4, rng, 500, 1.0, "c")
    plan = _plan(lt, rt, "c").plan
    fp_on = gated_fingerprint(plan)
    with fb.autotune_disabled():
        fp_off = gated_fingerprint(plan)
    assert fp_on != fp_off
    # the component is (active, Decisions) — the L1-policed carrier
    assert fp_on[-1][0] is True and fp_off[-1][0] is False
    assert isinstance(fp_on[-1][1], fb.Decisions)
    # without a store the component is the constant OFF state
    os.environ.pop("CYLON_TPU_OBS_DIR", None)
    assert gated_fingerprint(plan)[-1] == (False, fb.DECISIONS_OFF)


def test_decision_flip_recompiles_exactly_once(ctx4, rng, obs_env):
    """A tuned-decision flip re-enters the plan cache exactly once (the
    tuned-decision-in-fingerprint pin): misses == 1 cold compile + 1 per
    recorded flip, and a settled store stops recompiling."""
    lt, rt = _pair(ctx4, rng, 3000, 1.0, "d")
    lf = _plan(lt, rt, "d")
    m0 = tracing.get_count("plan.cache.miss")
    for _ in range(8):
        lf.collect()
    s = obs_store.store()
    flips = sum(p.get("flips", 0) for p in s.profiles.values())
    assert flips >= 1, "expected at least one decision flip on warm-up"
    assert tracing.get_count("plan.cache.miss") - m0 == 1 + flips
    # settled: no further misses
    m1 = tracing.get_count("plan.cache.miss")
    for _ in range(3):
        lf.collect()
    assert tracing.get_count("plan.cache.miss") == m1
    # the flipped decision is visible in the fingerprint component
    dec = gated_fingerprint(lf.plan)[-1][1]
    assert dec.semi_mode in ("on", "off", None) and dec != fb.Decisions(
        semi_mode="explore"
    )


def test_hysteresis_no_flap_on_alternating_observations(tmp_path):
    """Alternating evidence must never flip a decision: the candidate
    streak resets on every alternation, so the decision dict stays empty
    no matter how long the sequence runs (the no-flap pin at the
    decision layer; the plan-cache twin is the test above)."""
    d = str(tmp_path / "s")
    os.environ["CYLON_TPU_AUTOTUNE_MIN_OBS"] = "3"
    try:
        s = obs_store.ObsStore(d, compact_every=10 ** 9)
        for i in range(60):
            sel = 0.3 if i % 2 == 0 else 0.95  # mean ~0.625: mid-band
            s.record({"k": "exec", "fp": "flap", "world": 4,
                      "row_bytes": 8, "hot": 64, "sel": [sel, sel],
                      "sketch_built": 2})
        p = s.profiles["flap"]
        # each gate settles AT MOST once under alternating evidence
        # (semi to the mid-band static fallback, budget to its one
        # shrink) — never oscillates: total flips <= number of gates
        # that decided, and the semi decision is static/undecided
        assert p["flips"] <= 2, "alternating evidence must not oscillate"
        assert p["dec"].get("semi_mode") in (None, fb.STATIC)
        flips0 = p["flips"]
        # and CONSISTENT low-selectivity evidence from here flips the
        # semi gate exactly once more (to "on"), then stays
        for _ in range(30):
            s.record({"k": "exec", "fp": "flap", "world": 4,
                      "row_bytes": 8, "hot": 64, "sel": [0.05, 0.05],
                      "sketch_built": 2})
        assert p["dec"].get("semi_mode") == "on"
        assert p["flips"] == flips0 + 1
        s.close()
    finally:
        os.environ.pop("CYLON_TPU_AUTOTUNE_MIN_OBS", None)


def test_explain_analyze_tuned_golden(ctx4, rng, obs_env):
    """explain(analyze=True) annotates each tuned gate with
    ``tuned: <value> (was <static>, n=<obs>)``."""
    lt, rt = _pair(ctx4, rng, 3000, 1.0, "e")
    lf = _plan(lt, rt, "e")
    for _ in range(5):
        lf.collect()
    text = lf.explain(analyze=True)
    assert "Tuned gates:" in text
    assert "tuned: " in text and "(was " in text and ", n=" in text
    # the semi decision line names its static heuristic
    assert "semi_filter tuned: off (was payoff>=" in text
    # with autotune off the section is explicitly empty
    with fb.autotune_disabled():
        text_off = lf.explain(analyze=True)
    assert "Tuned gates: (none)" in text_off
    assert "tuned: " not in text_off


# ----------------------------------------------------------------------
# serve-bucket + spill proposers (decision layer)
# ----------------------------------------------------------------------
def test_serve_bucket_halves_toward_p99_target(tmp_path, monkeypatch):
    monkeypatch.setenv("CYLON_TPU_AUTOTUNE_MIN_OBS", "2")
    monkeypatch.setenv("CYLON_TPU_SERVE_P99_TARGET_MS", "1.0")
    monkeypatch.setenv("CYLON_TPU_SERVE_BATCH_MAX", "16")
    s = obs_store.ObsStore(str(tmp_path / "s"), compact_every=10 ** 9)
    for _ in range(4):  # 10 ms >> 1 ms target: halve the bucket
        s.record({"k": "lat", "fp": "serve", "s": 0.010, "b": 16})
    p = s.profiles["serve"]
    assert p["dec"].get("serve_bucket") == 8
    # the SERVING latency window (not the pooled lat histogram) resets
    # on flip so the NEW bucket is judged on its own evidence
    assert p["serve_lat"]["n"] < 4
    assert p["lat"]["n"] == 4  # the pooled history is untouched
    # fast observations under the new bucket walk it back up toward the
    # env max (a decision AT the max is recorded as None = untuned)
    for _ in range(8):
        s.record({"k": "lat", "fp": "serve", "s": 0.0001, "b": 8})
    assert p["dec"].get("serve_bucket") in (16, None)
    s.close()


def test_spill_tier_promotes_before_budget_line(tmp_path, monkeypatch):
    monkeypatch.setenv("CYLON_TPU_AUTOTUNE_MIN_OBS", "2")
    monkeypatch.setenv("CYLON_TPU_SPILL_DEVICE_BUDGET", str(1 << 20))
    s = obs_store.ObsStore(str(tmp_path / "s"), compact_every=10 ** 9)
    # staged at 90% of the budget: under the line (no spill yet) but
    # past the high-water mark -> promote to tier 1 preemptively
    for _ in range(4):
        s.record({"k": "exec", "fp": "sp", "world": 4, "row_bytes": 8,
                  "hot": 64, "staged": int(0.9 * (1 << 20)), "tier": 0})
    p = s.profiles["sp"]
    assert p["dec"].get("spill_tier") == 1
    # and choose_tier honors the promotion (forced env knob still wins)
    from cylon_tpu.parallel import spill

    assert spill.choose_tier(1024, tuned=1) == 1
    assert spill.choose_tier(1024, tuned=None) == 0
    s.close()


# ----------------------------------------------------------------------
# bounded histogram registry (obs/metrics.py satellite)
# ----------------------------------------------------------------------
def test_hist_registry_bounded_lru_evicts_to_store(monkeypatch, tmp_path):
    d = str(tmp_path / "h")
    monkeypatch.setenv("CYLON_TPU_OBS_DIR", d)
    monkeypatch.setenv("CYLON_TPU_TRACE_RING", "1")  # tiny capacity
    obs_store.reset_stores()
    obs_metrics.reset_latency()
    try:
        cap = obs_metrics.hist_capacity()
        assert cap == obs_metrics.HIST_CAP_MIN
        n_keys = cap + 50
        for i in range(n_keys):
            obs_metrics.observe_latency(f"hk{i}", 0.001 * (i + 1),
                                        label=f"lbl{i}")
        rep = obs_metrics.latency_report()
        assert len(rep) <= cap, "registry must stay bounded"
        # the oldest keys were evicted from memory...
        assert "hk0" not in rep and f"hk{n_keys - 1}" in rep
        # ...but their samples flushed to the store (no observation lost)
        s = obs_store.store()
        assert "hk0" in s.hists
        assert s.hists["hk0"]["n"] == 1
        assert s.hists["hk0"]["label"] == "lbl0"
        assert tracing.get_count("obs.hist.evicted") > 0
        # an LRU touch protects a hot key from eviction
        obs_metrics.observe_latency("hk_hot", 0.5)
        for i in range(cap - 1):
            obs_metrics.observe_latency(f"hk2_{i}", 0.001)
            obs_metrics.observe_latency("hk_hot", 0.5)
        assert "hk_hot" in obs_metrics.latency_report()
    finally:
        obs_metrics.reset_latency()
        obs_store.reset_stores()


# ----------------------------------------------------------------------
# traceview store modes
# ----------------------------------------------------------------------
def test_traceview_profiles_and_diff(tmp_path, capsys):
    import tools.traceview as tv

    d = str(tmp_path / "s")
    s = obs_store.ObsStore(d, compact_every=10 ** 9)
    for i in range(4):
        s.record({"k": "exec", "fp": "tv1", "world": 4, "row_bytes": 8,
                  "hot": 128, "coll": 10_000, "sel": [0.25]})
        s.record({"k": "lat", "fp": "tv1", "s": 0.01})
    s.compact()
    s.close()
    assert tv.main(["--profiles", "--obs-dir", d]) == 0
    out = capsys.readouterr().out
    assert "tv1" in out and "p99" in out and "semi sel 0.25" in out
    # bless a baseline, then diff clean
    assert tv.main(["--diff", "--obs-dir", d, "--save-baseline"]) == 0
    assert tv.main(["--diff", "--obs-dir", d]) == 0
    # regress coll-MB by 10x: the sentinel must flag and exit 1
    s2 = obs_store.ObsStore(d)
    for i in range(40):
        s2.record({"k": "exec", "fp": "tv1", "world": 4, "row_bytes": 8,
                   "hot": 128, "coll": 100_000})
    s2.close()
    capsys.readouterr()
    assert tv.main(["--diff", "--obs-dir", d]) == 1
    assert "REGRESSION" in capsys.readouterr().out


# ----------------------------------------------------------------------
# skew-trigger tuning from the straggler ledger (ISSUE 15 / ROADMAP-4)
# ----------------------------------------------------------------------
def _mild_skew_pair(ctx, rng, n, vname):
    """~2.2x hot/mean skew: 40% of rows share one key, permuted so every
    source shard holds the same mix — the band the static 4x-mean
    trigger ignores while the stage clocks measure a real straggler."""
    nh = int(n * 0.4)
    k = np.concatenate([
        np.zeros(nh, np.int32),
        rng.integers(1, n // 3, n - nh).astype(np.int32),
    ])
    k = rng.permutation(k)
    lt = ct.Table.from_pydict(
        ctx, {"k": k, vname: rng.random(n).astype(np.float32)}
    )
    rt = ct.Table.from_pydict(
        ctx, {"rk": k.copy(), "w": rng.random(n).astype(np.float32)}
    )
    return lt, rt


def test_skew_trigger_flips_once_and_matches_oracle(
    ctx4, rng, obs_env, monkeypatch
):
    """The tuned skew_trigger decision: observed straggler evidence (the
    stage clocks' max/mean shard-time ratio) flips the relay engagement
    ratio from the static 4x-mean to 2x on a mildly-skewed shape, with
    exactly one recompile per flip, strictly fewer shipped bytes after
    the flip, and bit-identical results to the CYLON_TPU_NO_AUTOTUNE
    oracle."""
    from cylon_tpu.obs import prof as obs_prof

    monkeypatch.setenv("CYLON_TPU_PROF", "1")
    obs_prof.reset()
    lt, rt = _mild_skew_pair(ctx4, rng, 12_000, "sk")
    lf = _plan(lt, rt, "sk")
    m0 = tracing.get_count("plan.cache.miss")
    bytes_per_run = []
    for _ in range(10):
        b0 = tracing.get_trace_report().get(
            "shuffle.exchanged_bytes", {}
        ).get("rows", 0)
        lf.collect()
        b1 = tracing.get_trace_report()["shuffle.exchanged_bytes"]["rows"]
        bytes_per_run.append(b1 - b0)
    s = obs_store.store()
    profs = [
        p for p in s.profiles.values()
        if p.get("dec", {}).get("skew_trigger") is not None
    ]
    assert profs, "the straggler evidence never tuned a skew_trigger"
    p = profs[0]
    assert p["dec"]["skew_trigger"] == fb.SKEW_TRIGGER_TUNED
    # straggler evidence was measured, and the shape sits in the mild
    # band the static trigger ignores
    assert p["strag_n"] >= 2
    assert p["strag_sum"] / p["strag_n"] >= fb.STRAGGLER_ENGAGE
    ratio = p["hot"] / max(p["mean_bucket"], 1)
    assert fb.SKEW_MILD_MIN <= ratio < 4.0, ratio
    # exactly one recompile per recorded flip (the fingerprint pin)
    flips = sum(q.get("flips", 0) for q in s.profiles.values())
    assert tracing.get_count("plan.cache.miss") - m0 == 1 + flips
    # the tuned trigger ships strictly fewer bytes than the static one
    assert bytes_per_run[-1] < bytes_per_run[0], bytes_per_run
    # the decision rides the fingerprint component
    dec = gated_fingerprint(lf.plan)[-1][1]
    assert dec.skew_trigger == fb.SKEW_TRIGGER_TUNED
    # differential oracle: results identical to the static-trigger run
    with fb.autotune_disabled():
        want = lf.collect().to_pandas().sort_values("k").reset_index(
            drop=True
        )
    got = lf.collect().to_pandas().sort_values("k").reset_index(drop=True)
    assert np.array_equal(got["k"].to_numpy(), want["k"].to_numpy())
    assert np.allclose(
        got[got.columns[-1]].to_numpy(), want[want.columns[-1]].to_numpy()
    )


def test_skew_trigger_stays_static_without_straggler_evidence(
    ctx4, rng, obs_env, monkeypatch
):
    """No profiler = no straggler evidence = no skew_trigger flip (the
    proposer demands measured shard-time ratios, not just a histogram),
    and a >=4x shape keeps the static trigger (it already fires)."""
    monkeypatch.delenv("CYLON_TPU_PROF", raising=False)
    lt, rt = _mild_skew_pair(ctx4, rng, 8_000, "sk2")
    lf = _plan(lt, rt, "sk2")
    for _ in range(5):
        lf.collect()
    s = obs_store.store()
    assert all(
        p.get("dec", {}).get("skew_trigger") is None
        for p in s.profiles.values()
    )
