"""Concurrent-dispatch hammer (ISSUE 7): the runtime proof behind the
L3 static certification.

graft-lint L3 certifies that eager dispatch is sync-free and that every
``ctx.__dict__``-hosted shared map (``_jit_cache`` / ``_plan_cache`` /
``_spec_cap_hints``) is lock-guarded; this file hammers exactly those
properties with real threads:

- 8 threads running mixed CACHED q3 / join / sort collects must produce
  bit-identical results to the serial oracle (exact-equality
  differential — same program, same inputs, same emit order);
- a cache STAMPEDE — 8 threads racing the first compile of one new plan
  fingerprint — must compile exactly once (1 miss, 7 hits: the losers
  block on the per-context lock, then hit the published entry) and all
  agree;
- concurrent first-touch materialization of ONE deferred result handle
  performs the count fetch once (``Table._mat_lock``).

Rows are deliberately small: this is a race hunt, not a throughput
bench — tier-1 runs it unmarked.
"""
import os
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import numpy.testing as npt
import pytest

import cylon_tpu as ct
from cylon_tpu import col
from cylon_tpu.utils import tracing

# XLA:CPU executes each virtual device's collective participant on a
# host thread; with a single host core the backend's dispatch pool has
# exactly device-count slots, so TWO programs in flight can strand one
# program's last participant behind the other's parked rendezvous — a
# guaranteed cross-run deadlock (observed: run A holds 7 threads at its
# rendezvous while its rank 6's slot runs run B's rank 3, which waits
# on A). That is a backend thread-pool limitation, not the property
# under test — the lock discipline these hammers certify is already
# statically checked by graft-lint L3, and the runtime hammer needs
# real thread parallelism to hunt races anyway.
pytestmark = pytest.mark.skipif(
    (os.cpu_count() or 1) < 2,
    reason="thread hammer deadlocks XLA:CPU's collective rendezvous "
    "on a single-core host (dispatch-pool exhaustion across runs)",
)


def _mk_tables(ctx, rng, n=1500):
    ta = ct.Table.from_pydict(
        ctx,
        {
            "k": rng.integers(0, 40, n).astype(np.int32),
            "v": rng.normal(size=n).astype(np.float32),
        },
    )
    tb = ct.Table.from_pydict(
        ctx,
        {
            "rk": rng.integers(0, 40, n).astype(np.int32),
            "w": rng.normal(size=n).astype(np.float32),
        },
    )
    return ta, tb


def _assert_identical(got, want):
    assert list(got) == list(want)
    for name in want:
        npt.assert_array_equal(got[name], want[name])


def test_hammer_mixed_cached_plans(ctx8, rng):
    """8 threads x 6 mixed cached collects each, differentially against
    the serial oracle. Every plan was compiled (and its kernels built)
    before the hammer, so this exercises the lock-free hit path and
    concurrent kernel execution, not compilation."""
    ta, tb = _mk_tables(ctx8, rng)
    plans = [
        ta.lazy()
        .join(tb.lazy(), left_on="k", right_on="rk")
        .filter(col("w") > 0.0)
        .groupby("k", {"v": "sum"}),
        ta.lazy().join(tb.lazy(), left_on="k", right_on="rk"),
        ta.lazy().sort(["k", "v"]),
    ]
    oracle = [p.collect().to_pydict() for p in plans]  # warm + oracle

    def worker(i):
        out = []
        for j in range(6):
            idx = (i + j) % len(plans)
            out.append((idx, plans[idx].collect().to_pydict()))
        return out

    with ThreadPoolExecutor(max_workers=8) as ex:
        for res in ex.map(worker, range(8)):
            for idx, snap in res:
                _assert_identical(snap, oracle[idx])


def test_cache_stampede_compiles_once(ctx8, rng):
    """8 threads race the FIRST compile of one fresh plan fingerprint:
    the per-context lock admits one compiler; the losers block, then hit
    the published entry — exactly 1 miss, 7 hits, identical results."""
    ta, tb = _mk_tables(ctx8, rng, n=800)
    # a literal no other test uses: guarantees a fresh fingerprint
    lf = (
        ta.lazy()
        .join(tb.lazy(), left_on="k", right_on="rk")
        .filter(col("w") > 0.1234567)
        .groupby("k", {"v": "sum"})
    )
    tracing.reset_trace()
    barrier = threading.Barrier(8)

    def worker(_):
        barrier.wait()
        return lf.collect().to_pydict()

    with ThreadPoolExecutor(max_workers=8) as ex:
        snaps = list(ex.map(worker, range(8)))
    assert tracing.get_count("plan.cache.miss") == 1
    assert tracing.get_count("plan.cache.hit") == 7
    for s in snaps[1:]:
        _assert_identical(s, snaps[0])


def test_concurrent_materialize_single_fetch(ctx8, rng):
    """Many threads forcing ONE deferred result handle: _mat_lock admits
    one fetch; everyone sees the same (possibly compacted) counts."""
    from cylon_tpu.analysis.hostsync import sync_monitor

    ta, _ = _mk_tables(ctx8, rng)
    mask = ta.column("k").data < 20
    res = ta.filter(mask)  # deferred counts: no sync yet
    barrier = threading.Barrier(8)

    def worker(_):
        barrier.wait()
        return res.row_count

    with sync_monitor() as events:
        with ThreadPoolExecutor(max_workers=8) as ex:
            counts = list(ex.map(worker, range(8)))
    fetches = [e for e in events if e.site == "_materialize_counts"]
    assert len(fetches) == 1, [(e.site, e.line) for e in events]
    assert len(set(counts)) == 1
    # differential: the deferred+concurrent path equals the serial value
    serial = ta.filter(mask)
    serial._materialize()
    assert counts[0] == serial.row_count


def test_hammer_with_eager_dispatch_mix(ctx8, rng):
    """Interleave cached-plan collects with raw eager dispatch chains
    (deferred-count handles created and materialized across threads)."""
    ta, tb = _mk_tables(ctx8, rng, n=1000)
    q3 = (
        ta.lazy()
        .join(tb.lazy(), left_on="k", right_on="rk")
        .filter(col("w") > 0.0)
        .groupby("k", {"v": "sum"})
    )
    oracle_plan = q3.collect().to_pydict()
    mask = ta.column("k").data < 25
    oracle_eager = ta.filter(mask).unique(["k"]).to_pydict()

    def worker(i):
        if i % 2:
            return ("plan", q3.collect().to_pydict())
        return ("eager", ta.filter(mask).unique(["k"]).to_pydict())

    with ThreadPoolExecutor(max_workers=8) as ex:
        for kind, snap in ex.map(worker, range(16)):
            _assert_identical(
                snap, oracle_plan if kind == "plan" else oracle_eager
            )


def test_hammer_traced_eight_disjoint_trees(ctx8, rng, monkeypatch, tmp_path):
    """ISSUE-8 acceptance under the hammer: 8 threads collecting the
    cached q3 plan concurrently with the tracer ON must record 8
    DISJOINT query span trees (per-thread contextvar isolation — the
    flat tracer interleaved them into one blob), the exported Chrome
    trace must carry 8 tracks, and the process-global rollup must remain
    exactly the cross-query sum."""
    from cylon_tpu.obs import export as obs_export

    monkeypatch.setenv("CYLON_TPU_TRACE", "tree")
    ta, tb = _mk_tables(ctx8, rng, n=1200)
    q3 = (
        ta.lazy()
        .join(tb.lazy(), left_on="k", right_on="rk")
        .filter(col("w") > 0.0)
        .groupby("k", {"v": "sum"})
    )
    oracle = q3.collect().to_pydict()  # warm: hammer runs the hit path
    obs_export.reset_ring()
    tracing.reset_trace()
    barrier = threading.Barrier(8)

    def worker(_):
        barrier.wait()
        return q3.collect().to_pydict()

    with ThreadPoolExecutor(max_workers=8) as ex:
        for snap in ex.map(worker, range(8)):
            _assert_identical(snap, oracle)

    qs = [q for q in obs_export.traces() if q.kind == "plan"]
    assert len(qs) == 8, f"expected 8 query traces, got {len(qs)}"
    # disjoint trees: no span object shared between any two traces, and
    # every trace carries its own full plan pipeline
    seen_spans = set()
    for q in qs:
        ids = set(map(id, q.all_spans()))
        assert not (ids & seen_spans), "traces share span nodes"
        seen_spans |= ids
        names = {sp.name for sp in q.all_spans()}
        assert "plan.execute" in names
        assert any(n.startswith("plan.node.") for n in names)
        assert q.counters["plan.cache.hit"][0] == 1
        assert q.device_resolved_s() is not None
    # rollup preserved: the global counter is exactly the per-trace sum
    assert tracing.get_count("plan.cache.hit") == 8
    # the Chrome export carries 8 tracks, one per query
    path = str(tmp_path / "hammer.json")
    obs_export.write_chrome(path, qs)
    doc = obs_export.load_chrome(path)
    assert obs_export.validate_chrome(doc) == []
    tracks = obs_export.summarize(doc)
    assert len(tracks) == 8
    assert all(t["spans"] > 0 for t in tracks.values())
