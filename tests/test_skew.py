"""Adversarial skew tests for the shuffle (VERDICT round-1 items 4/9).

The reference handles ragged partition sizes by streaming byte buffers
(arrow/arrow_all_to_all.cpp:83-141); under XLA static shapes the equivalent
is the multi-round balanced-capacity exchange: a hot (src,dst) bucket drains
over ceil(count/cap) rounds instead of inflating every bucket to the global
max. These tests pin that behavior: correctness under one-hot keys, output
capacity NOT blown up P x by one hot source, the fused pipeline's in-graph
respill, and jit-cache stability across repeated calls.
"""
import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.engine import round_cap


def _ctx8(devices):
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:8]))


def test_one_hot_key_shuffle(devices):
    """Every row carries the SAME key: all rows route to one shard. Must
    complete without assert/error and preserve content."""
    ctx = _ctx8(devices)
    n = 2048
    t = ct.Table.from_pydict(
        ctx, {"k": np.zeros(n, np.int32), "v": np.arange(n, dtype=np.float32)}
    )
    s = t.shuffle(["k"])
    assert s.row_count == n
    assert s.row_counts.max() == n  # all rows on the one target shard
    got = np.sort(s.to_pandas()["v"].to_numpy())
    assert np.array_equal(got, np.arange(n, dtype=np.float32))


def test_skewed_source_shuffle_capacity(devices):
    """One shard holds a big hot-key block, others are tiny. The single-round
    design would size EVERY bucket at the hot bucket (output capacity
    world * round_cap(big)); the multi-round exchange must come out near
    round_cap(rows actually landing on the hottest shard)."""
    ctx = _ctx8(devices)
    big, small = 4096, 16
    rng = np.random.default_rng(3)
    shards = []
    for i in range(8):
        m = big if i == 0 else small
        shards.append(
            {"k": np.full(m, 7, np.int32), "v": rng.normal(size=m).astype(np.float32)}
        )
    t = ct.Table.from_shards(ctx, shards)
    total = big + 7 * small
    s = t.shuffle(["k"])
    assert s.row_count == total
    assert s.row_counts.max() == total  # single hot destination
    # no P x padding: physical capacity tracks the hot shard's real load,
    # not world * max_bucket (= 8 * 4096 rows here)
    assert s.shard_cap <= 2 * round_cap(total)
    # content preserved (multiset of v values)
    got = np.sort(s.to_pandas()["v"].to_numpy())
    exp = np.sort(np.concatenate([sh["v"] for sh in shards]))
    assert np.allclose(got, exp)


def test_skewed_distributed_join(devices):
    """Distributed join under hot-key skew matches pandas exactly."""
    ctx = _ctx8(devices)
    rng = np.random.default_rng(4)
    n = 4000
    # half the rows share one key, the rest are uniform
    k = np.where(rng.random(n) < 0.5, 3, rng.integers(0, 500, n)).astype(np.int32)
    v = rng.normal(size=n).astype(np.float32)
    k2 = rng.integers(0, 500, 300).astype(np.int32)
    w = rng.normal(size=300).astype(np.float32)
    lt = ct.Table.from_pydict(ctx, {"k": k, "v": v})
    rt = ct.Table.from_pydict(ctx, {"k": k2, "w": w})
    out = lt.distributed_join(rt, on="k", how="inner")
    exp = pd.DataFrame({"k": k, "v": v}).merge(
        pd.DataFrame({"k": k2, "w": w}), on="k", how="inner"
    )
    assert out.row_count == len(exp)
    gp = out.to_pandas().sort_values(["k_x", "v", "w"]).reset_index(drop=True)
    ep = exp.rename(columns={"k": "k_x"}).sort_values(["k_x", "v", "w"]).reset_index(
        drop=True
    )
    pd.testing.assert_frame_equal(
        gp[["k_x", "v", "w"]], ep[["k_x", "v", "w"]], check_dtype=False,
        check_exact=False, rtol=1e-5,
    )


def test_distributed_sort_with_duplicate_block(devices):
    """Range partitioner under a massive duplicate run must still produce a
    globally sorted result."""
    ctx = _ctx8(devices)
    rng = np.random.default_rng(5)
    n = 3000
    k = np.where(rng.random(n) < 0.6, 42, rng.integers(0, 1000, n)).astype(np.int32)
    t = ct.Table.from_pydict(ctx, {"k": k})
    s = t.distributed_sort("k")
    got = s.to_pandas()["k"].to_numpy()
    assert np.array_equal(got, np.sort(k))


def test_fused_respill_recovers_hot_bucket(devices):
    """The pipeline's in-graph respill: bucket_cap at HALF the hot bucket
    plus one respill round completes with zero overflow and exact counts."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from cylon_tpu.ops import join as _j
    from cylon_tpu.parallel.pipeline import make_distributed_join_step

    world, shard_cap = 4, 32
    mesh = Mesh(np.array(devices[:world]), ("dp",))
    sh = NamedSharding(mesh, PartitionSpec("dp"))
    key = np.zeros(world * shard_cap, np.int32)  # one key -> one hot bucket
    val = np.arange(world * shard_cap, dtype=np.float32)
    cols = [
        (jax.device_put(jnp.asarray(key), sh), None),
        (jax.device_put(jnp.asarray(val), sh), None),
    ]
    counts = jax.device_put(jnp.full((world,), shard_cap, jnp.int32), sh)

    # cap 16 = half of each shard's 32-row hot bucket; respill=1 drains it
    step = make_distributed_join_step(
        mesh, "dp", l_key_idx=(0,), r_key_idx=(0,), how=_j.INNER,
        bucket_cap=16, join_cap=(world * shard_cap) ** 2, respill=1,
    )
    out_cols, out_counts, overflow = step((cols, counts, cols, counts), ())
    assert int(np.asarray(overflow).sum()) == 0
    assert int(np.asarray(out_counts).sum()) == (world * shard_cap) ** 2

    # respill=0 at the same cap must flag the overflow instead
    step0 = make_distributed_join_step(
        mesh, "dp", l_key_idx=(0,), r_key_idx=(0,), how=_j.INNER,
        bucket_cap=16, join_cap=(world * shard_cap) ** 2, respill=0,
    )
    _, _, overflow0 = step0((cols, counts, cols, counts), ())
    assert int(np.asarray(overflow0).sum()) > 0


def _chunk_budget(t, max_bucket: int, k: int) -> int:
    """Byte budget that targets ~k rounds for table ``t`` — the planner's
    own inverse (shuffle.budget_for_rounds), so the sweep can't drift."""
    from cylon_tpu.parallel import shuffle as _sh

    return _sh.budget_for_rounds(
        max_bucket, k, t.world_size, _sh.exchange_row_bytes(t._flat_cols())
    )


@pytest.mark.parametrize("k", [1, 4, 16])
def test_chunked_all_rows_to_one_shard(devices, k):
    """All-rows-to-one-shard skew (one-hot key) under K ∈ {1, 4, 16}
    chunked rounds: round count matches the planner's prediction for the
    analytically known send counts, and the output is differential-equal
    to the unchunked shuffle."""
    from cylon_tpu.parallel import shuffle as _sh
    from cylon_tpu.utils.tracing import report, reset_trace

    ctx = _ctx8(devices)
    n, world = 2048, 8
    t = ct.Table.from_pydict(
        ctx,
        {"k": np.zeros(n, np.int32),
         "v": np.arange(n, dtype=np.float32)},
    )
    # every shard sends its whole even split to ONE destination
    max_bucket = n // world
    budget = _chunk_budget(t, max_bucket, k)
    # the planner's own prediction on the analytically known count matrix
    counts = np.zeros((world, world), np.int64)
    counts[:, 0] = max_bucket  # (the hot destination's column; dst index
    # is hash-dependent but the count DISTRIBUTION is exact)
    row_bytes = _sh.exchange_row_bytes(t._flat_cols())
    _cap, expect_rounds = _sh.plan_rounds(counts, row_bytes, world, budget)

    # the subject is the chunking engine's round arithmetic over PLAIN
    # int32 lanes under the PADDED plan: run under the lane-packing
    # oracle (the wire-narrowed codec's smaller row bytes legitimately
    # need fewer rounds — test_lane_pack.py covers those plans) AND the
    # skew-split oracle (the adaptive schedule legitimately collapses the
    # one-hot round count — test_skew_split_* pins that behavior)
    from cylon_tpu.ops import stats as _lp
    from cylon_tpu.parallel import spill as _sp

    reset_trace()
    with _lp.disabled(), _sp.skew_disabled():
        s = t.shuffle(["k"], byte_budget=budget)
    got_rounds = int(report("shuffle.")["shuffle.rounds"]["rows"])
    assert got_rounds == expect_rounds
    if k > 1:
        assert got_rounds >= k  # the budget actually forced chunking
    assert s.row_count == n
    assert s.row_counts.max() == n  # all rows on the one target shard
    base = t.shuffle(["k"], byte_budget=1 << 40)
    assert (s.row_counts == base.row_counts).all()
    assert np.array_equal(
        np.sort(s.to_pandas()["v"].to_numpy()),
        np.sort(base.to_pandas()["v"].to_numpy()),
    )


@pytest.mark.parametrize("k", [1, 4, 16])
def test_chunked_empty_shard_skew(devices, k):
    """Empty-shard skew (one shard owns EVERY row, seven are empty) under
    K ∈ {1, 4, 16}: chunked rounds drain the single hot source and the
    result matches the unchunked shuffle row-for-row after sorting."""
    from cylon_tpu.utils.tracing import report, reset_trace

    ctx = _ctx8(devices)
    n, world = 2048, 8
    rng = np.random.default_rng(11)
    shards = [
        {"k": rng.integers(0, 97, n).astype(np.int32),
         "v": rng.normal(size=n).astype(np.float32)}
    ] + [
        {"k": np.empty(0, np.int32), "v": np.empty(0, np.float32)}
        for _ in range(world - 1)
    ]
    t = ct.Table.from_shards(ctx, shards)
    assert (t.row_counts[1:] == 0).all()
    # the hot source spreads ~n/world rows per destination bucket
    budget = _chunk_budget(t, -(-n // world), k)
    from cylon_tpu.ops import stats as _lp

    reset_trace()
    with _lp.disabled():  # pin the PLAIN-lane round plan (see above)
        s = t.shuffle(["k"], byte_budget=budget)
    rounds = int(report("shuffle.")["shuffle.rounds"]["rows"])
    if k >= 4:
        assert rounds > 1  # chunking engaged on the hot source
    base = t.shuffle(["k"], byte_budget=1 << 40)
    assert s.row_count == n
    assert (s.row_counts == base.row_counts).all()
    sp = s.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    bp = base.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    assert np.array_equal(sp["k"].to_numpy(), bp["k"].to_numpy())
    assert np.allclose(sp["v"].to_numpy(), bp["v"].to_numpy())


def test_skew_split_one_hot_adaptive(devices):
    """Satellite pin (ISSUE 10): the skew-adaptive schedule splits the
    one-hot hot bucket onto the host relay — the traced
    ``shuffle.skew_split`` counter fires, total shipped bytes (collective
    + relay) land >= 40% below the padded plan's, and the output matches
    the padded-plan oracle exactly. Runs with lane packing ENABLED: the
    old skew pins ran only under the lane-pack oracle."""
    from cylon_tpu.parallel import spill as _sp
    from cylon_tpu.utils.tracing import report, reset_trace

    ctx = _ctx8(devices)
    n = 2048
    t = ct.Table.from_pydict(
        ctx,
        {"k": np.zeros(n, np.int32),
         "v": np.arange(n, dtype=np.float32)},
    )
    reset_trace()
    s = t.shuffle(["k"])
    r = report("shuffle.")
    assert r["shuffle.skew_split"]["count"] >= 1
    assert int(r["shuffle.skew_split"]["rows"]) > 0
    adaptive_bytes = int(r["shuffle.exchanged_bytes"]["rows"]) + int(
        r["shuffle.spill.relay_bytes"]["rows"]
    )
    reset_trace()
    with _sp.skew_disabled():
        base = t.shuffle(["k"])
    rb = report("shuffle.")
    assert "shuffle.skew_split" not in rb
    padded_bytes = int(rb["shuffle.exchanged_bytes"]["rows"])
    # the acceptance bar: >= 40% fewer shipped bytes at 8-way one-hot
    assert adaptive_bytes <= 0.6 * padded_bytes, (
        adaptive_bytes, padded_bytes,
    )
    assert s.row_count == n
    assert (s.row_counts == base.row_counts).all()
    assert np.array_equal(
        np.sort(s.to_pandas()["v"].to_numpy()),
        np.sort(base.to_pandas()["v"].to_numpy()),
    )


def test_skew_split_non_skewed_plans_byte_identical(devices):
    """Satellite pin: a NON-skewed histogram must plan byte-identically
    with the skew gate on or off — same (cap, K), same exchanged bytes,
    no relay counter — so the adaptive planner provably costs nothing on
    the plans the padded engine already handled well."""
    from cylon_tpu.parallel import spill as _sp
    from cylon_tpu.utils.tracing import report, reset_trace

    ctx = _ctx8(devices)
    rng = np.random.default_rng(9)
    t = ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(0, 997, 4096).astype(np.int32),
         "v": rng.normal(size=4096).astype(np.float32)},
    )
    reset_trace()
    s_on = t.shuffle(["k"])
    r_on = report("shuffle.")
    reset_trace()
    with _sp.skew_disabled():
        s_off = t.shuffle(["k"])
    r_off = report("shuffle.")
    assert "shuffle.skew_split" not in r_on
    assert "shuffle.spill.relay_bytes" not in r_on
    assert (
        r_on["shuffle.exchanged_bytes"]["rows"]
        == r_off["shuffle.exchanged_bytes"]["rows"]
    )
    assert r_on["shuffle.rounds"]["rows"] == r_off["shuffle.rounds"]["rows"]
    assert (s_on.row_counts == s_off.row_counts).all()
    assert s_on.shard_cap == s_off.shard_cap


def test_skew_split_schedule_planner_units():
    """plan_schedule host arithmetic: one-hot splits (quota + relay cover
    every bucket exactly), uniform stays the plan_rounds identity, and
    the marginal-skew guard keeps the padded plan."""
    from cylon_tpu.parallel import shuffle as _sh
    from cylon_tpu.parallel import spill as _sp

    world, rb = 8, 8
    budget = 1 << 40
    # one-hot: every source sends 256 rows to destination 0
    m = np.zeros((world, world), np.int64)
    m[:, 0] = 256
    sched = _sp.plan_schedule(m, rb, world, budget)
    assert sched.adaptive
    shipped = np.minimum(m, sched.quota) + sched.relay
    assert (shipped == m).all()  # relay + quota cover every bucket
    base_cap, base_k = _sh.plan_rounds(m, rb, world, budget)
    assert sched.coll_row_slots(world) < base_k * base_cap * world * world
    # uniform: byte-identical passthrough of plan_rounds
    u = np.full((world, world), 64, np.int64)
    su = _sp.plan_schedule(u, rb, world, budget)
    cap_u, k_u = _sh.plan_rounds(u, rb, world, budget)
    assert (su.bucket_cap, su.n_rounds, su.relay) == (cap_u, k_u, None)
    # mild skew below the savings bar: stays padded
    mild = np.full((world, world), 64, np.int64)
    mild[0, 0] = 96
    assert not _sp.plan_schedule(mild, rb, world, budget).adaptive


def test_shuffle_jit_cache_stable(devices):
    """Repeated shuffles with same shapes/statics reuse one compiled kernel
    (VERDICT weak 9: pin compile counts)."""
    ctx = _ctx8(devices)
    rng = np.random.default_rng(6)

    def mk(seed):
        r = np.random.default_rng(seed)
        return ct.Table.from_pydict(
            ctx,
            {"k": r.integers(0, 100, 1000).astype(np.int32),
             "v": r.normal(size=1000).astype(np.float32)},
        )

    t = mk(0)
    _ = t.shuffle(["k"])
    n_keys = len(ctx._jit_cache)
    sizes = {k: f._cache_size() for k, f in ctx._jit_cache.items()}
    for seed in (1, 2, 3):
        _ = mk(seed).shuffle(["k"])
    assert len(ctx._jit_cache) == n_keys, "new kernel keys appeared"
    for k, f in ctx._jit_cache.items():
        if k in sizes:
            assert f._cache_size() == sizes[k], f"kernel {k} retraced"
