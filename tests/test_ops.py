"""The production ops surface (ISSUE 12): resource ledger, SLO monitor,
live metrics endpoint, footprint-fed admission, multi-writer obs store.

Pinned properties:

- LEDGER: Table construction registers device bytes, GC unregisters
  them (weakref finalizers — no syncs anywhere), shared buffers never
  double-count, the peak watermark survives frees, and the leak
  detector flags query-attributed tables with creation sites.
- FOOTPRINT LOOP: ledger-attributed exec records build a per-
  fingerprint footprint distribution; the feedback re-coster settles a
  pow2 p95 ``footprint`` decision under the standard hysteresis; the
  serving scheduler leases it instead of the static input-bytes
  estimate (``CYLON_TPU_NO_AUTOTUNE=1`` restores the static oracle) —
  small-footprint shapes admit under budgets the static estimate would
  shed, with zero lost results under the 16-thread hammer.
- SLO: rolling-window p99/shed/leak rules transition OK->BREACH and
  back as breaches age out; transitions land in the flight ring.
- ENDPOINT: /metrics parses under the strict Prometheus line checker
  and carries quantiles + ledger + SLO; /healthz flips on breach;
  /queries serves the ring as JSON; traceview --live renders it.
- STORE: per-process journals merge on load; compaction by one writer
  never drops another's records; two real processes share a directory.
"""
import gc
import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu import col
from cylon_tpu.obs import export as obs_export
from cylon_tpu.obs import metrics as obs_metrics
from cylon_tpu.obs import resource as obs_resource
from cylon_tpu.obs import slo as obs_slo
from cylon_tpu.obs import store as obs_store
from cylon_tpu.plan import feedback as fb
from cylon_tpu.plan.lazy import gated_fingerprint
from cylon_tpu.serve import ServeOverloadError, ServeScheduler
from cylon_tpu.utils import tracing


@pytest.fixture
def ledger_on(monkeypatch):
    """Enable the ledger (via the tracing gate) with a fresh ring."""
    monkeypatch.setenv("CYLON_TPU_TRACE", "tree")
    obs_export.reset_ring()
    yield
    obs_export.reset_ring()


@pytest.fixture
def obs_env(tmp_path, monkeypatch):
    """A fresh observation store + fast hysteresis."""
    d = str(tmp_path / "obs")
    monkeypatch.setenv("CYLON_TPU_OBS_DIR", d)
    monkeypatch.setenv("CYLON_TPU_AUTOTUNE_MIN_OBS", "2")
    obs_store.reset_stores()
    yield d
    obs_store.reset_stores()


def _mk(ctx, rng, n, vname="v"):
    return ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(0, 30, n).astype(np.int32),
         vname: rng.integers(-50, 50, n).astype(np.float32)},
    )


def _q3(ta, tb, vname="v"):
    return (
        ta.lazy()
        .join(tb.lazy(), left_on="k", right_on="rk")
        .filter(col("w") > 0.0)
        .groupby("k", {vname: "sum"})
    )


def _pair(ctx, rng, n, vname="v"):
    ta = _mk(ctx, rng, n, vname)
    tb = ct.Table.from_pydict(
        ctx,
        {"rk": rng.integers(0, 30, n).astype(np.int32),
         "w": rng.integers(-50, 50, n).astype(np.float32)},
    )
    return ta, tb


# ----------------------------------------------------------------------
# the resource ledger
# ----------------------------------------------------------------------
def test_ledger_tracks_device_bytes_and_peak(ctx8, rng, ledger_on):
    led = obs_resource.ledger(ctx8)
    # flush cycle garbage earlier tests left (plans/traces whose tables
    # die only at a gc pass): the baseline below must measure a settled
    # ledger, not whenever the collector last happened to run
    gc.collect()
    base = led.snapshot()["device_bytes"]
    t = _mk(ctx8, rng, 4096)
    snap = led.snapshot()
    grew = snap["device_bytes"] - base
    assert grew > 0, "a new table must register device bytes"
    assert snap["device_peak"] >= snap["device_bytes"]
    peak = led.snapshot()["device_peak"]
    del t
    gc.collect()
    after = led.snapshot()
    assert after["device_bytes"] == base, "GC must return the bytes"
    assert after["device_peak"] == peak, "the peak watermark survives frees"


def test_ledger_shared_buffers_not_double_counted(ctx8, rng, ledger_on):
    led = obs_resource.ledger(ctx8)
    t = _mk(ctx8, rng, 2048)
    before = led.snapshot()["device_bytes"]
    views = [t.project(["k"]), t.rename({"v": "w"})]
    assert led.snapshot()["device_bytes"] == before, (
        "projections share Column buffers: zero new ledger bytes"
    )
    del views
    gc.collect()
    assert led.snapshot()["device_bytes"] == before, (
        "dropping a sharing view must not free the shared buffers"
    )


def test_ledger_disabled_is_inert(ctx8, rng, monkeypatch):
    monkeypatch.delenv("CYLON_TPU_TRACE", raising=False)
    monkeypatch.delenv("CYLON_TPU_OBS_DIR", raising=False)
    monkeypatch.delenv("CYLON_TPU_METRICS_PORT", raising=False)
    assert not obs_resource.enabled()
    led = obs_resource.ledger(ctx8)
    before = led.snapshot()["device_bytes"]
    t = _mk(ctx8, rng, 1024)
    assert led.snapshot()["device_bytes"] == before, (
        "a disabled ledger must register nothing"
    )
    del t


def test_leak_detector_flags_creation_site(ctx8, rng, ledger_on):
    led = obs_resource.ledger(ctx8)
    ta, tb = _pair(ctx8, rng, 1024)
    lf = _q3(ta, tb)
    held = lf.collect()  # the "leak": held past its query's finish
    leaks = led.leaks(grace_s=0.0)
    mine = [lk for lk in leaks if "test_ops.py" in lk["site"]]
    assert mine, f"held result must be flagged with its creation site: {leaks}"
    assert all(lk["bytes"] > 0 and lk["age_s"] >= 0 for lk in mine)
    del held
    gc.collect()
    after = [
        lk for lk in led.leaks(grace_s=0.0) if "test_ops.py" in lk["site"]
    ]
    assert len(after) < len(mine), "the freed result is no longer a leak"
    # a generous grace flags nothing this young
    ta2, tb2 = _pair(ctx8, rng, 512)
    held2 = _q3(ta2, tb2).collect()
    assert not [
        lk for lk in led.leaks(grace_s=3600.0)
        if "test_ops.py" in lk["site"]
    ]
    del held2


# ----------------------------------------------------------------------
# the footprint loop: ledger evidence -> tuned admission estimate
# ----------------------------------------------------------------------
def test_exec_records_carry_footprint(ctx8, rng, obs_env, ledger_on):
    ta, tb = _pair(ctx8, rng, 2048, vname="fa")
    lf = _q3(ta, tb, vname="fa")
    for _ in range(3):
        lf.collect()
    s = obs_store.store()
    key = fb.base_key(gated_fingerprint(lf.plan)[:-1])
    p = s.profiles[key]
    assert p["foot"]["n"] >= 3, "every execution must journal its footprint"
    assert p["foot"]["max"] > 0


def test_footprint_decision_feeds_admission(ctx8, rng, obs_env, monkeypatch):
    """The ROADMAP-4 close: a shape whose observed footprint is far
    below the static input-bytes estimate admits under a budget the
    static estimate sheds at — and CYLON_TPU_NO_AUTOTUNE restores the
    oracle."""
    from cylon_tpu.plan import lower as plan_lower

    ta, tb = _pair(ctx8, rng, 30_000, vname="fb")
    lf = _q3(ta, tb, vname="fb")
    static_est = ct.serve.estimate_query_bytes([ta, tb])
    # key the profile the way submit will: scan_tables assigns the DFS
    # scan ordinals the fingerprint embeds
    plan_lower.scan_tables(lf.plan)
    key = fb.base_key(gated_fingerprint(lf.plan)[:-1])
    # seed the store with consistent small-footprint evidence (4 records
    # at min_obs=2: propose, then flip under hysteresis)
    s = obs_store.store()
    for _ in range(4):
        s.record({"k": "exec", "fp": key, "dev": 3000})
    dec = fb.decisions_for(gated_fingerprint(lf.plan)[:-1])
    assert dec.footprint == 4096, f"pow2(p95 of 3000B) = 4096, got {dec}"
    # a budget between the tuned footprint and the static estimate:
    # tuned admits, the static oracle sheds
    budget = max(dec.footprint * 4, 65_536)
    assert static_est > budget, (
        f"test needs static est {static_est} above the {budget} budget"
    )
    monkeypatch.setenv("CYLON_TPU_SERVE_INFLIGHT_BYTES", str(budget))
    sched = ServeScheduler(ctx8, auto_start=False)
    admits_before = tracing.get_count("autotune.footprint_admit")
    fut = sched.submit(lf)  # tuned: admitted
    assert tracing.get_count("autotune.footprint_admit") == admits_before + 1
    assert fut.est_bytes == dec.footprint
    with fb.autotune_disabled():
        with pytest.raises(ServeOverloadError):
            sched.submit(lf)  # oracle: static estimate exceeds the budget
    sched.run_pending()
    assert fut.result(timeout=60).row_count > 0
    sched.close()


def test_footprint_hammer_admits_more_with_zero_lost_results(
    ctx8, rng, obs_env, monkeypatch
):
    """Under a budget sized for ~2 static estimates: tuned footprints
    admit the whole 16-query wave (deterministic nowait count), the
    static oracle admits strictly fewer — and the 16-thread concurrent
    hammer loses NOTHING in either regime (every binding's result
    equals its serial collect)."""
    from cylon_tpu.plan import lower as plan_lower

    bindings = [_pair(ctx8, rng, 8_000, vname="fh") for _ in range(16)]
    lfs = [_q3(ta, tb, vname="fh") for ta, tb in bindings]
    with pytest.MonkeyPatch.context() as mp:
        # serial oracles with the store off: their (large, intermediate-
        # heavy) real footprints must not drown the seeded evidence
        mp.delenv("CYLON_TPU_OBS_DIR")
        oracle = [lf.collect().to_pydict() for lf in lfs]
    static_est = ct.serve.estimate_query_bytes(list(bindings[0]))
    plan_lower.scan_tables(lfs[0].plan)
    key = fb.base_key(gated_fingerprint(lfs[0].plan)[:-1])
    s = obs_store.store()
    for _ in range(4):
        s.record({"k": "exec", "fp": key, "dev": 3000})
    assert fb.decisions_for(gated_fingerprint(lfs[0].plan)[:-1]).footprint
    # freeze further flips: the hammer's own evidence must not re-key
    # plans mid-flight while we count admission behavior
    monkeypatch.setenv("CYLON_TPU_AUTOTUNE_MIN_OBS", "100000")
    monkeypatch.setenv(
        "CYLON_TPU_SERVE_INFLIGHT_BYTES", str(int(static_est * 2.5))
    )

    def admitted_nowait():
        """Deterministic admission census: nowait submits on a
        worker-less scheduler — every accepted query holds its lease
        until consumed, so the count is exactly how much concurrency
        the budget buys under this regime."""
        sched = ServeScheduler(ctx8, auto_start=False)
        futs = []
        try:
            for lf in lfs:
                try:
                    futs.append(sched.submit(lf, block=False))
                except ServeOverloadError:
                    pass
            n = len(futs)
            sched.run_pending()
            for f in futs:
                f.result(timeout=120)
            return n
        finally:
            sched.close()

    tuned_admitted = admitted_nowait()
    with fb.autotune_disabled():
        oracle_admitted = admitted_nowait()
    # 16 concurrent ~4KB tuned leases fit the ~2.5-estimate budget;
    # only ~2 static estimates do
    assert tuned_admitted == 16, f"tuned admitted {tuned_admitted}/16"
    assert oracle_admitted < tuned_admitted, (
        f"oracle admitted {oracle_admitted}, tuned {tuned_admitted}"
    )

    # the concurrent zero-loss hammer runs under a roomy budget: the
    # admission behavior was already pinned deterministically above, and
    # the tight budget would (correctly, per the documented 2x hard cap)
    # shed unconsumed-result bursts depending on thread timing
    monkeypatch.setenv(
        "CYLON_TPU_SERVE_INFLIGHT_BYTES", str(int(static_est * 20))
    )

    def hammer():
        sched = ServeScheduler(ctx8)
        try:
            with ThreadPoolExecutor(max_workers=16) as ex:
                return [
                    t.to_pydict() for t in ex.map(
                        lambda lf: sched.submit(lf).result(timeout=120),
                        lfs,
                    )
                ]
        finally:
            sched.close()

    tuned_results = hammer()
    with fb.autotune_disabled():
        oracle_results = hammer()
    for i in range(16):  # zero lost results, both regimes
        for got, label in (
            (tuned_results[i], f"tuned binding {i}"),
            (oracle_results[i], f"oracle binding {i}"),
        ):
            assert list(got) == list(oracle[i]), label
            a = pd.DataFrame(got).sort_values(list(got)).reset_index(drop=True)
            b = pd.DataFrame(oracle[i]).sort_values(
                list(oracle[i])
            ).reset_index(drop=True)
            pd.testing.assert_frame_equal(a, b, check_dtype=False, obj=label)


# ----------------------------------------------------------------------
# the SLO monitor
# ----------------------------------------------------------------------
def test_slo_p99_burn_and_recovery(monkeypatch):
    monkeypatch.setenv("CYLON_TPU_SERVE_P99_TARGET_MS", "1.0")
    obs_metrics.reset_latency()
    obs_export.reset_ring()
    mon = obs_slo.SLOMonitor(window=60.0)
    assert mon.evaluate().get("p99:slow") is None  # baseline, no samples
    for _ in range(8):
        obs_metrics.observe_latency("slow", 0.5)  # 500 ms >> 1 ms target
    st = mon.evaluate()
    assert st["p99:slow"] == obs_slo.STATE_BREACH
    ok, reasons = mon.healthy()
    assert not ok and any("p99:slow" in r for r in reasons)
    # the transition is a structured flight-ring record
    slo_recs = [q for q in obs_export.traces() if q.kind == "slo"]
    assert any(
        q.attrs.get("slo.rule") == "p99:slow"
        and q.attrs.get("slo.to") == "BREACH"
        for q in slo_recs
    ), [q.name for q in slo_recs]
    # within target -> OK (new monitor, fast queries only)
    obs_metrics.reset_latency()
    mon2 = obs_slo.SLOMonitor(window=60.0)
    mon2.evaluate()
    for _ in range(8):
        obs_metrics.observe_latency("fast", 0.0001)
    assert mon2.evaluate()["p99:fast"] == obs_slo.STATE_OK


def test_slo_breach_ages_out_of_window(monkeypatch):
    monkeypatch.setenv("CYLON_TPU_SERVE_P99_TARGET_MS", "1.0")
    obs_metrics.reset_latency()
    mon = obs_slo.SLOMonitor(window=0.2)
    mon.evaluate()
    for _ in range(8):
        obs_metrics.observe_latency("aging", 0.5)
    assert mon.evaluate()["p99:aging"] == obs_slo.STATE_BREACH
    time.sleep(0.3)  # no new samples: the breach ages out
    mon.evaluate()
    st = mon.evaluate()
    assert st.get("p99:aging", obs_slo.STATE_OK) == obs_slo.STATE_OK
    ok, _ = mon.healthy()
    assert ok


def test_slo_shed_storm_and_leak_rules(ctx8, rng, monkeypatch):
    obs_metrics.reset_latency()
    # shed rates are judged per WINDOW (the denominator clamps to it):
    # 5 sheds over a 2 s window is a storm, over 60 s it would be WARN
    mon = obs_slo.SLOMonitor(window=2.0)
    mon.evaluate()
    ta, tb = _pair(ctx8, rng, 256)
    lf = _q3(ta, tb)
    monkeypatch.setenv("CYLON_TPU_SERVE_INFLIGHT_BYTES", "1")
    sched = ServeScheduler(ctx8, auto_start=False)
    for _ in range(5):
        with pytest.raises(ServeOverloadError):
            sched.submit(lf, block=False)
    st = mon.evaluate()
    assert st["shed"] == obs_slo.STATE_BREACH, st
    assert st["leak"] == obs_slo.STATE_OK, (
        "admission-budget sheds are load, not leak — the reason split "
        "is what lets the rules tell them apart"
    )
    sched.close()


# ----------------------------------------------------------------------
# the Prometheus exposition + the HTTP endpoint
# ----------------------------------------------------------------------
def test_prometheus_exposition_strict_format(ctx8, rng, ledger_on):
    ta, tb = _pair(ctx8, rng, 1024)
    _q3(ta, tb).collect()
    text = obs_export.prometheus_text()
    assert obs_export.validate_prometheus(text) == []
    assert "cylon_tpu_ledger_device_bytes" in text
    assert "cylon_tpu_query_latency_seconds" in text
    assert 'quantile="0.99"' in text
    # the checker itself must reject malformed lines
    assert obs_export.validate_prometheus("bad line here\n")
    assert obs_export.validate_prometheus('x{unclosed="v} 1\n')
    assert obs_export.validate_prometheus("# TYPE x bogus\n")
    assert obs_export.validate_prometheus(
        "# TYPE x counter\n# TYPE x counter\nx 1\n"
    )


def _get(port, path):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_ops_server_endpoints(ctx8, rng, ledger_on, monkeypatch):
    monkeypatch.delenv("CYLON_TPU_SERVE_P99_TARGET_MS", raising=False)
    obs_slo.reset_monitor()
    ta, tb = _pair(ctx8, rng, 1024)
    _q3(ta, tb).collect()
    srv = obs_export.OpsServer(0)
    port = srv.start()
    try:
        st, text = _get(port, "/metrics")
        assert st == 200
        assert obs_export.validate_prometheus(text) == []
        assert "cylon_tpu_slo_state" in text
        st, body = _get(port, "/healthz")
        assert st == 200 and json.loads(body)["ok"] is True
        st, body = _get(port, "/queries")
        assert st == 200
        ring = json.loads(body)
        assert isinstance(ring, list) and ring
        assert {"qid", "kind", "name", "wall_ms"} <= set(ring[-1])
        st, _ = _get(port, "/nope")
        assert st == 404
    finally:
        srv.stop()
        obs_slo.reset_monitor()


def test_healthz_flips_on_breach_and_recovers(ctx8, rng, monkeypatch):
    monkeypatch.setenv("CYLON_TPU_SLO_WINDOW_S", "0.3")
    obs_slo.reset_monitor()
    srv = obs_export.OpsServer(0)
    port = srv.start()
    try:
        assert _get(port, "/healthz")[0] == 200  # baseline sample
        ta, tb = _pair(ctx8, rng, 256)
        lf = _q3(ta, tb)
        monkeypatch.setenv("CYLON_TPU_SERVE_INFLIGHT_BYTES", "1")
        sched = ServeScheduler(ctx8, auto_start=False)
        for _ in range(5):
            with pytest.raises(ServeOverloadError):
                sched.submit(lf, block=False)
        st, body = _get(port, "/healthz")
        assert st == 503, body
        assert any("shed" in r for r in json.loads(body)["reasons"])
        sched.close()
        deadline = time.monotonic() + 10
        while _get(port, "/healthz")[0] != 200:
            assert time.monotonic() < deadline, "healthz must recover"
            time.sleep(0.1)
    finally:
        srv.stop()
        obs_slo.reset_monitor()


def test_new_metric_names_are_declared():
    for name in (
        "serve.shed.admission_budget",
        "serve.shed.queue_depth",
        "serve.shed.unconsumed_cap",
        "ledger.device_bytes",
        "ledger.live_tables",
        "slo.state.shed",
        "slo.transitions",
        "autotune.footprint_admit",
        "shuffle.spill.disk_bytes",
    ):
        assert obs_metrics.is_declared(name), name


# ----------------------------------------------------------------------
# traceview: --serving (the PR 9 rollup, untested until now) + --live
# ----------------------------------------------------------------------
def test_traceview_serving_rollup(ctx8, rng, ledger_on, tmp_path, capsys):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools import traceview

    sched = ServeScheduler(ctx8, auto_start=False)
    bindings = [_pair(ctx8, rng, 512) for _ in range(4)]
    obs_export.reset_ring()
    futs = [sched.submit(_q3(ta, tb)) for ta, tb in bindings]
    sched.run_pending()
    for f in futs:
        f.result(timeout=60)
    sched.close()
    path = str(tmp_path / "ring.json")
    obs_export.write_chrome(path)
    rc = traceview.main([path, "--serving"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "serving summary" in out
    assert "fingerprint" in out and "p99" in out
    # the batched group renders occupancy + the serve.* counters
    assert "batches:" in out, out


def test_traceview_live(ctx8, rng, ledger_on, capsys):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools import traceview

    obs_slo.reset_monitor()
    ta, tb = _pair(ctx8, rng, 512)
    _q3(ta, tb).collect()
    srv = obs_export.OpsServer(0)
    port = srv.start()
    try:
        rc = traceview.main(["--live", f"http://127.0.0.1:{port}"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "healthz: 200" in out
        assert "per-fingerprint latency" in out
        assert "flight ring" in out
    finally:
        srv.stop()
        obs_slo.reset_monitor()


# ----------------------------------------------------------------------
# the multi-writer observation store
# ----------------------------------------------------------------------
def _exec_rec(fp):
    return {"k": "exec", "fp": fp, "world": 4, "row_bytes": 8, "hot": 16}


def test_multi_writer_merge_on_load(tmp_path):
    d = str(tmp_path / "mw")
    a = obs_store.ObsStore(d, writer_id="a")
    b = obs_store.ObsStore(d, writer_id="b")
    for _ in range(5):
        a.record(_exec_rec("shape_a"))
    for _ in range(7):
        b.record(_exec_rec("shape_b"))
    a.close()
    b.close()
    assert os.path.exists(os.path.join(d, "journal-a.jsonl"))
    assert os.path.exists(os.path.join(d, "journal-b.jsonl"))
    r = obs_store.ObsStore(d, writer_id="reader")
    assert r.profiles["shape_a"]["n"] == 5
    assert r.profiles["shape_b"]["n"] == 7
    r.close()


def test_compaction_preserves_other_writers(tmp_path):
    d = str(tmp_path / "mwc")
    a = obs_store.ObsStore(d, writer_id="a", compact_every=10 ** 9)
    b = obs_store.ObsStore(d, writer_id="b", compact_every=10 ** 9)
    for _ in range(4):
        a.record(_exec_rec("shape_a"))
    for _ in range(6):
        b.record(_exec_rec("shape_b"))
    b.flush()  # make b's buffered tail durable for a's fold
    a.compact()  # folds BOTH journals, truncates only a's
    assert os.path.getsize(os.path.join(d, "journal-a.jsonl")) == 0
    assert os.path.getsize(os.path.join(d, "journal-b.jsonl")) > 0
    # a's adopted in-memory view now includes b's records
    assert a.profiles["shape_b"]["n"] == 6
    # b keeps appending after a's compaction; a fresh reader sees all of
    # it exactly once (the snapshot's per-writer jseqs dedup the replay)
    for _ in range(3):
        b.record(_exec_rec("shape_b"))
    b.compact()
    r = obs_store.ObsStore(d, writer_id="reader")
    assert r.profiles["shape_a"]["n"] == 4
    assert r.profiles["shape_b"]["n"] == 9
    r.close()
    a.close()
    b.close()


def test_compaction_reaps_dead_writer_journals(tmp_path):
    """A journal left by an exited process is unlinked by the next
    compaction (records safe in the snapshot; a dead pid can never
    append again), and its stale jseq entry drops one compaction later —
    the shared directory stays O(live writers), not O(process
    lifetimes). Non-pid writer ids are never touched."""
    d = str(tmp_path / "reap")
    p = subprocess.Popen([sys.executable, "-c", "pass"])
    p.wait()
    dead = str(p.pid)  # a real, provably dead pid
    w = obs_store.ObsStore(d, writer_id=dead)
    for _ in range(3):
        w.record(_exec_rec("dead_shape"))
    w.close()
    live = obs_store.ObsStore(d, writer_id="live_x", compact_every=10 ** 9)
    live.record(_exec_rec("live_shape"))
    live.compact()
    assert not os.path.exists(os.path.join(d, f"journal-{dead}.jsonl")), (
        "dead writer's journal must be reaped"
    )
    assert live.profiles["dead_shape"]["n"] == 3, "records survive in snap"
    live.compact()  # the stale jseq entry drops once the file is gone
    with open(os.path.join(d, "snapshot.json")) as f:
        snap = json.load(f)
    assert dead not in snap["jseqs"]
    r = obs_store.ObsStore(d, writer_id="reader")
    assert r.profiles["dead_shape"]["n"] == 3
    assert r.profiles["live_shape"]["n"] == 1
    r.close()
    live.close()


def test_legacy_single_writer_journal_still_reads(tmp_path):
    d = str(tmp_path / "legacy")
    os.makedirs(d)
    with open(os.path.join(d, "journal.jsonl"), "w") as f:
        for i in range(3):
            f.write(json.dumps(
                {"k": "exec", "fp": "old_shape", "i": i + 1, "hot": 4}
            ) + "\n")
        f.write('{"torn...')  # torn tail: skipped, never fatal
    s = obs_store.ObsStore(d, writer_id="new")
    assert s.profiles["old_shape"]["n"] == 3
    assert s.skipped_lines == 1
    s.close()


def test_two_real_processes_share_one_store(tmp_path):
    """The satellite's concurrent two-process append test: a child
    process writes its own journal while the parent writes; a fresh
    load merges both."""
    d = str(tmp_path / "procs")
    child_src = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "from cylon_tpu.obs import store\n"
        f"s = store.ObsStore({d!r})\n"
        "for _ in range(40):\n"
        "    s.record({'k': 'exec', 'fp': 'child_shape', 'hot': 2})\n"
        "s.close()\n"
        "print('child done')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", child_src],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
        cwd=os.path.join(os.path.dirname(__file__), ".."),
    )
    parent = obs_store.ObsStore(d, writer_id="parent")
    for _ in range(40):
        parent.record(_exec_rec("parent_shape"))
    out, err = proc.communicate(timeout=180)
    assert proc.returncode == 0, err.decode()[-2000:]
    parent.close()
    r = obs_store.ObsStore(d, writer_id="reader")
    assert r.profiles["parent_shape"]["n"] == 40
    assert r.profiles["child_shape"]["n"] == 40
    r.close()
