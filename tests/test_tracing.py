"""Tracing subsystem (utils/tracing.py)."""
import numpy as np

import cylon_tpu as ct
from cylon_tpu.utils import get_trace_report, reset_trace, span


def test_span_registry():
    reset_trace()
    with span("unit.phase", rows=10):
        pass
    with span("unit.phase", rows=5):
        pass
    rep = get_trace_report()
    assert rep["unit.phase"]["count"] == 2
    assert rep["unit.phase"]["rows"] == 15
    assert rep["unit.phase"]["total_s"] >= 0


def test_ops_record_spans(local_ctx, rng):
    reset_trace()
    t = ct.Table.from_pydict(local_ctx, {
        "k": rng.integers(0, 10, 100), "v": rng.normal(size=100)
    })
    t.sort("k")
    t.join(t, on="k")
    t.groupby("k", {"v": "sum"})
    rep = get_trace_report()
    assert rep["sort"]["count"] >= 1
    assert rep["sort"]["rows"] >= 100
    assert rep["join.speculative"]["count"] >= 1
    assert rep["groupby.emit"]["count"] >= 1


def test_shuffle_records_spans(ctx8, rng):
    reset_trace()
    t = ct.Table.from_pydict(ctx8, {"k": rng.integers(0, 10, 64)})
    t.shuffle(["k"])
    rep = get_trace_report()
    assert rep["shuffle.count"]["count"] == 1
    assert rep["shuffle.exchange"]["count"] == 1


def test_report_helper_prefix_filter(local_ctx):
    from cylon_tpu.utils.tracing import bump, report, span

    reset_trace()
    with span("unit.a"):
        pass
    bump("unit.b", rows=3)
    bump("other.c")
    full = report()
    assert {"unit.a", "unit.b", "other.c"} <= set(full)
    only = report("unit.")
    assert set(only) == {"unit.a", "unit.b"}
    assert only["unit.b"]["rows"] == 3
    # report returns copies: mutating it must not poison the registry
    only["unit.b"]["rows"] = 999
    assert report("unit.")["unit.b"]["rows"] == 3
