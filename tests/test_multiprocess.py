"""REAL multi-process execution: 2 OS processes x 2 CPU devices each, global
mesh of 4, collectives over Gloo — the analog of the reference's
``mpirun -np N`` tests (cpp/test/CMakeLists.txt:44-49: N identical processes,
each owning its partition, every Distributed* op a collective all ranks
enter).

Each worker process:
- initializes via ``TPUConfig(coordinator_address=..., num_processes=2,
  process_id=pid)`` (the MPI_Init analog, context.py);
- builds tables via ``Table.from_encoded_shards`` providing ONLY its local
  shards (remote entries None + global counts) — per-rank ingestion, no
  global host buffer;
- runs distributed_join / distributed_sort / scalar aggregates and checks
  results against the pandas oracle (identical on every process).
"""
import os
import socket
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
    )
    os.environ["CYLON_TPU_PLATFORM"] = "cpu"
    import numpy as np
    import pandas as pd
    from collections import OrderedDict

    import cylon_tpu as ct
    from cylon_tpu.column import Column

    pid = int(sys.argv[1])
    port = sys.argv[2]
    ctx = ct.CylonContext.init_distributed(ct.TPUConfig(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid,
    ))
    import jax
    assert jax.process_count() == 2, jax.process_count()
    world = ctx.world_size
    assert world == 4, world
    assert ctx.rank == pid  # reference GetRank analog

    # deterministic global data, sharded 4 ways; each process ENCODES ONLY
    # the shards its devices own
    rng = np.random.default_rng(99)
    N = 400
    gk = rng.integers(0, 40, N).astype(np.int64)
    gv = rng.normal(size=N)
    g2 = rng.integers(0, 40, N).astype(np.int64)
    gw = rng.normal(size=N)
    counts = np.array([100, 100, 100, 100], np.int64)
    offs = np.concatenate([[0], np.cumsum(counts)])

    devices = list(ctx.mesh.devices.flat)

    def my_shards(cols):
        shards = []
        for i in range(world):
            if devices[i].process_index != jax.process_index():
                shards.append(None)
                continue
            lo, hi = int(offs[i]), int(offs[i + 1])
            shards.append(OrderedDict(
                (name, Column.encode_host(arr[lo:hi])) for name, arr in cols.items()
            ))
        return shards

    ta = ct.Table.from_encoded_shards(ctx, my_shards({"k": gk, "v": gv}), counts=counts)
    tb = ct.Table.from_encoded_shards(ctx, my_shards({"k": g2, "w": gw}), counts=counts)

    a = pd.DataFrame({"k": gk, "v": gv})
    b = pd.DataFrame({"k": g2, "w": gw})
    exp = a.merge(b, on="k")

    j = ta.distributed_join(tb, on="k", how="inner")
    assert j.row_count == len(exp), (j.row_count, len(exp))

    # fused + hash-sliced rounds across REAL process boundaries (the
    # lax.scan body's collectives run over Gloo here)
    jf = ta.distributed_join(tb, on="k", how="inner", mode="fused",
                             num_slices=2)
    assert jf.row_count == len(exp), (jf.row_count, len(exp))

    s = float(ta.sum("v"))
    assert np.isclose(s, gv.sum()), (s, gv.sum())

    srt = ta.distributed_sort("k")
    assert srt.row_count == N

    ctx.barrier()
    print(f"proc {pid} MULTIPROC-OK join={j.row_count}", flush=True)
    """
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.mark.xfail(
    reason=(
        "jax 0.4.37's CPU backend cannot run multi-process collectives: "
        "worker ranks fail with 'Multiprocess computations aren't "
        "implemented on the CPU backend'. Fixed upstream by the "
        "cross-host CPU collectives (Gloo) work in newer jax; on real "
        "TPU pods the same code path is exercised by the MULTICHIP "
        "dryruns. Pre-seed failure, unchanged since PR 1 — xfail so "
        "tier-1 reports fully green and real regressions are unmissable."
    ),
    strict=False,
)
def test_two_process_distributed_ops(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=540)
            outs.append(out)
    finally:
        # a deadlocked rank (e.g. peer crashed pre-barrier) must not leak
        # orphan processes pinning the coordinator port
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert f"proc {i} MULTIPROC-OK" in out, out[-1500:]
