"""compute module + Series: elementwise ops vs the pandas oracle.

Reference analog: python/test/test_compute.py over data/compute.pyx.
"""
import operator

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu import compute as cc


@pytest.fixture
def tbl(local_ctx, rng):
    df = pd.DataFrame({
        "a": rng.integers(0, 10, 50).astype(np.int64),
        "b": rng.normal(size=50),
    })
    df.loc[3, "b"] = np.nan
    return ct.Table.from_pandas(local_ctx, df), df


def test_compare_scalar(tbl):
    t, df = tbl
    for op, pop in [(operator.gt, "gt"), (operator.le, "le"), (operator.eq, "eq")]:
        out = cc.table_compare_op(t.project(["a"]), 5, op).to_pandas()["a"]
        exp = getattr(df["a"], pop)(5)
        assert (out.to_numpy() == exp.to_numpy()).all()


def test_compare_table(tbl, local_ctx):
    t, df = tbl
    other = ct.Table.from_pandas(local_ctx, pd.DataFrame({"a2": df["a"].to_numpy()[::-1].copy()}))
    out = cc.table_compare_op(t.project(["a"]), other, operator.lt).to_pandas()["a"]
    exp = df["a"].to_numpy() < df["a"].to_numpy()[::-1]
    assert (out.to_numpy() == exp).all()


def test_string_scalar_compare(local_ctx):
    t = ct.Table.from_pydict(local_ctx, {"s": ["b", "a", "c", "b"]})
    eq = cc.table_compare_op(t, "b", operator.eq).to_pandas()["s"]
    assert eq.tolist() == [True, False, False, True]
    lt = cc.table_compare_op(t, "b", operator.lt).to_pandas()["s"]
    assert lt.tolist() == [False, True, False, False]
    # absent value: ordering still works off insertion position
    ge = cc.table_compare_op(t, "ab", operator.ge).to_pandas()["s"]
    assert ge.tolist() == [True, False, True, True]


def test_math_scalar_and_table(tbl, local_ctx):
    t, df = tbl
    out = cc.math_op(t.project(["b"]), "mul", 2.5).to_pandas()["b"]
    exp = df["b"] * 2.5
    assert np.allclose(out.to_numpy(), exp.to_numpy(), equal_nan=True)
    other = ct.Table.from_pandas(local_ctx, pd.DataFrame({"c": np.arange(50) + 1.0}))
    out2 = cc.math_op(t.project(["b"]), "div", other).to_pandas()["b"]
    exp2 = df["b"] / (np.arange(50) + 1.0)
    assert np.allclose(out2.to_numpy(), exp2.to_numpy(), equal_nan=True)


def test_division_by_zero_guard(tbl):
    t, _ = tbl
    with pytest.raises(ZeroDivisionError):
        cc.division_op(t.project(["a"]), "/", 0)


def test_neg_invert_isnull(tbl):
    t, df = tbl
    out = cc.neg(t.project(["a"])).to_pandas()["a"]
    assert (out.to_numpy() == -df["a"].to_numpy()).all()
    b = cc.table_compare_op(t.project(["a"]), 5, operator.lt)
    inv = cc.invert(b).to_pandas()["a"]
    assert (inv.to_numpy() == ~(df["a"] < 5).to_numpy()).all()
    nulls = cc.is_null(t).to_pandas()
    assert nulls["b"].sum() == 1 and not nulls["a"].any()


def test_is_in(tbl, local_ctx):
    t, df = tbl
    out = cc.is_in(t.project(["a"]), [1, 3, 7]).to_pandas()["a"]
    assert (out.to_numpy() == df["a"].isin([1, 3, 7]).to_numpy()).all()
    ts = ct.Table.from_pydict(local_ctx, {"s": ["x", "y", "z"]})
    outs = cc.is_in(ts, ["y", "q"]).to_pandas()["s"]
    assert outs.tolist() == [False, True, False]


def test_is_in_null_is_false(local_ctx):
    t = ct.Table.from_pydict(local_ctx, {"v": np.array([1.0, np.nan, 3.0])})
    out = cc.is_in(t, [1.0, 3.0]).to_pandas()["v"]
    assert out.tolist() == [True, False, True]


def test_drop_na(local_ctx):
    df = pd.DataFrame({"x": [1.0, np.nan, 3.0], "y": [np.nan, np.nan, 1.0]})
    t = ct.Table.from_pandas(local_ctx, df)
    assert cc.drop_na(t, "any", axis=0).row_count == 1
    assert cc.drop_na(t, "all", axis=0).row_count == 2
    assert cc.drop_na(t, "any", axis=1).column_names == []
    t2 = ct.Table.from_pandas(local_ctx, pd.DataFrame({"x": [1.0, 2.0], "y": [np.nan, np.nan]}))
    assert cc.drop_na(t2, "all", axis=1).column_names == ["x"]


def test_nunique_and_unique(tbl):
    t, df = tbl
    nu = cc.nunique(t)
    assert nu["a"] == df["a"].nunique()
    assert nu["b"] == df["b"].nunique()


def test_map_columns(tbl):
    import jax.numpy as jnp

    t, df = tbl
    out = cc.map_columns(t.project(["b"]), jnp.exp).to_pandas()["b"]
    assert np.allclose(out.to_numpy(), np.exp(df["b"].to_numpy()), equal_nan=True)


# ---------------------------------------------------------------- Series

def test_series_basic(local_ctx):
    s = ct.Series([3, 1, 2], name="v", ctx=local_ctx)
    assert s.name == "v" and s.shape == (3,) and len(s) == 3
    assert s.sum() == 6 and s.min() == 1 and s.max() == 3
    assert s.sort_values().to_numpy().tolist() == [1, 2, 3]
    assert s.sort_values(ascending=False).to_numpy().tolist() == [3, 2, 1]


def test_series_ops(local_ctx):
    s = ct.Series(np.array([1.0, 2.0, 3.0]), name="v", ctx=local_ctx)
    assert ((s + 1).to_numpy() == np.array([2.0, 3.0, 4.0])).all()
    assert ((s * s).to_numpy() == np.array([1.0, 4.0, 9.0])).all()
    m = s > 1.5
    assert m.to_numpy().tolist() == [False, True, True]
    assert s[m].to_numpy().tolist() == [2.0, 3.0]
    assert (-s).to_numpy().tolist() == [-1.0, -2.0, -3.0]


def test_series_null_handling(local_ctx):
    s = ct.Series(np.array([1.0, np.nan, 3.0]), name="v", ctx=local_ctx)
    assert s.count() == 2
    assert s.isnull().to_numpy().tolist() == [False, True, False]
    assert s.fillna(0.0).to_numpy().tolist() == [1.0, 0.0, 3.0]
    assert s.nunique() == 2


def test_series_isin_astype(local_ctx):
    s = ct.Series(np.array([1, 2, 3], np.int64), name="v", ctx=local_ctx)
    assert s.isin([2, 9]).to_numpy().tolist() == [False, True, False]
    assert s.astype(np.float32).to_numpy().dtype == np.float32


def test_is_in_no_string_truncation(local_ctx):
    """Probe strings longer than the dictionary's width must not truncate
    (compute.py is_in object-dtype probe)."""
    t = ct.Table.from_pydict(local_ctx, {"s": ["x", "y", "z"]})
    out = cc.is_in(t, ["xy"]).to_pandas()["s"]
    assert out.tolist() == [False, False, False]


def test_is_in_integer_domain_exact(local_ctx):
    """Integer membership stays in the integer domain: 2^53+1 and 2^53 are
    distinct (a float64 round-trip would collapse them)."""
    big = 2**53
    t = ct.Table.from_pydict(local_ctx, {"v": np.array([big, big + 1], np.int64)})
    out = cc.is_in(t, [big + 1]).to_pandas()["v"]
    assert out.tolist() == [False, True]
    # float values that are integral still match integer columns
    t2 = ct.Table.from_pydict(local_ctx, {"v": np.array([1, 2, 3], np.int32)})
    assert cc.is_in(t2, [2.0]).to_pandas()["v"].tolist() == [False, True, False]
    # non-integral float can never match an int column
    assert cc.is_in(t2, [2.5]).to_pandas()["v"].tolist() == [False, False, False]


def test_compare_table_width_mismatch(tbl, local_ctx):
    other = ct.Table.from_pydict(local_ctx, {"z": np.arange(50)})
    with pytest.raises(ValueError, match="same number"):
        cc.table_compare_op(tbl[0], other, operator.lt)


def test_division_numpy_zero_guard(tbl):
    with pytest.raises(ZeroDivisionError):
        cc.division_op(tbl[0].project(["a"]), "/", np.int64(0))


def test_pyrange_index():
    r = ct.PyRangeIndex(start=0, stop=10, step=2)
    assert r.index_values.tolist() == [0, 2, 4, 6, 8]
    r2 = ct.PyRangeIndex(data=np.arange(0, 10, 2))
    assert (r2.start, r2.stop, r2.step) == (0, 10, 2)
    with pytest.raises(ValueError):
        ct.PyRangeIndex(data=np.array([1, 2, 4]))
    assert ct.IntegerIndex(np.array([1, 2])).index_values.tolist() == [1, 2]
    with pytest.raises(ValueError):
        ct.IntegerIndex(np.array([1.5]))


def test_nunique_distributed(ctx8):
    """Values present on several shards must count once (compute.py nunique
    distributed_unique path)."""
    t = ct.Table.from_pydict(ctx8, {"v": np.tile(np.arange(5), 40)})
    assert cc.nunique(t)["v"] == 5


def test_pyrange_index_rejects_floats():
    with pytest.raises(ValueError, match="integers"):
        ct.PyRangeIndex(data=[0.5, 1.5, 2.5])
