"""Randomized pandas-parity fuzzing of the relational ops.

The round-2 kernels (merged kv-sort join probe, sorted-space set algebra,
chained lexsorts) are all tie/padding/sentinel-sensitive, so beyond the
fixed goldens this sweeps random shapes x dtypes x null densities against
pandas — the same oracle the reference's python tests use (SURVEY.md §4.2).
"""
import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct


def _rand_frame(rng, n, keyspace, dtype, null_p):
    if dtype == "int32":
        k = rng.integers(-keyspace, keyspace, n).astype(np.int32).astype(object)
    elif dtype == "float32":
        base = rng.integers(-keyspace, keyspace, n).astype(np.float32)
        # exercise -0.0 / duplicate float keys
        base = np.where(rng.random(n) < 0.1, -0.0, base).astype(np.float32)
        k = base.astype(object)
    else:  # string
        k = rng.choice([f"s{i}" for i in range(keyspace)], n).astype(object)
    if null_p:
        k[rng.random(n) < null_p] = None
    return pd.DataFrame({"k": k, "v": rng.normal(size=n).astype(np.float32)})


CASES = [
    (0, 37, 5, "int32", 0.0),
    (1, 64, 3, "int32", 0.2),
    (2, 100, 8, "float32", 0.0),
    (3, 51, 4, "float32", 0.15),
    (4, 80, 6, "string", 0.0),
    (5, 45, 3, "string", 0.25),
    (6, 1, 2, "int32", 0.0),     # single row
    (7, 33, 1, "int32", 0.0),    # all-equal keys (hot key)
]


# ctx8 (8-device mesh context) comes from tests/conftest.py


def _norm(df):
    """Order-free normal form: stringified keys (0.0 folded onto -0.0),
    rows sorted, index dropped."""
    out = df.copy()
    def canon(v):
        if v is None or (isinstance(v, float) and np.isnan(v)):
            return "\0null"
        if isinstance(v, (bool, np.bool_)):
            return str(bool(v))
        if isinstance(v, (int, float, np.integer, np.floating)):
            return str(float(v) + 0.0)  # folds -0.0 and int/float reprs
        return str(v)

    out["k"] = out["k"].map(canon)
    return out.sort_values(list(out.columns), na_position="last").reset_index(
        drop=True
    )


@pytest.mark.parametrize("seed,n,keyspace,dtype,null_p", CASES)
def test_join_all_hows_vs_pandas(ctx8, seed, n, keyspace, dtype, null_p):
    rng = np.random.default_rng(seed)
    a = _rand_frame(rng, n, keyspace, dtype, null_p)
    b = _rand_frame(rng, max(n // 2, 1), keyspace, dtype, null_p)
    env = ct.CylonEnv(config=ct.TPUConfig())
    da = ct.DataFrame(a)
    db = ct.DataFrame(b)
    for how in ("inner", "left", "right", "outer"):
        got = da.merge(db, on="k", how=how, env=env)
        want = a.merge(b, on="k", how=how)
        assert len(got) == len(want), (how, len(got), len(want))
        g = got.to_pandas()[["k", "v_x", "v_y"]]
        w = want[["k", "v_x", "v_y"]]
        pd.testing.assert_frame_equal(
            _norm(g), _norm(w), check_dtype=False, atol=1e-6
        )


@pytest.mark.parametrize("seed,n,keyspace,dtype,null_p", CASES)
def test_setops_vs_pandas(ctx8, seed, n, keyspace, dtype, null_p):
    rng = np.random.default_rng(seed + 100)
    a = _rand_frame(rng, n, keyspace, dtype, null_p)
    b = _rand_frame(rng, max(n // 2, 1), keyspace, dtype, null_p)
    # set-ops key on ALL columns; quantize v to force cross-table equal rows
    a["v"] = (a["v"] * 2).round(0).astype(np.float32)
    b["v"] = (b["v"] * 2).round(0).astype(np.float32)
    ta = ct.Table.from_pandas(ctx8, a)
    tb = ct.Table.from_pandas(ctx8, b)

    ad = a.drop_duplicates()
    bd = b.drop_duplicates()
    both = ad.merge(bd, on=["k", "v"])
    assert ta.distributed_unique().row_count == len(ad)
    assert ta.distributed_intersect(tb).row_count == len(both)
    assert ta.distributed_subtract(tb).row_count == len(ad) - len(both)
    assert (
        ta.distributed_union(tb).row_count
        == len(pd.concat([ad, bd]).drop_duplicates())
    )


@pytest.mark.parametrize("keep", ["first", "last"])
@pytest.mark.parametrize("seed", [0, 1])
def test_unique_keep_first_last_vs_pandas(ctx8, keep, seed):
    """keep-first/last pick the right representative ROW (not just count):
    the v payload disambiguates which duplicate survived."""
    rng = np.random.default_rng(seed + 400)
    n = 90
    a = pd.DataFrame(
        {
            "k": rng.integers(0, 7, n).astype(np.int32),
            "v": np.arange(n, dtype=np.float32),  # unique -> identifies rows
        }
    )
    ta = ct.Table.from_pandas(ctx8, a)
    got = ta.distributed_unique(columns=["k"], keep=keep).to_pandas()
    want = a.drop_duplicates(subset=["k"], keep=keep)
    assert len(got) == len(want)
    g = got.sort_values("k").reset_index(drop=True)
    w = want.sort_values("k").reset_index(drop=True)
    np.testing.assert_array_equal(g["k"].to_numpy(), w["k"].to_numpy())
    np.testing.assert_array_equal(g["v"].to_numpy(), w["v"].to_numpy())


@pytest.mark.parametrize("seed,n,keyspace", [(0, 120, 6), (1, 73, 3)])
def test_groupby_full_agg_matrix_vs_pandas(ctx8, seed, n, keyspace):
    """min/max/var/std/nunique/median across the mesh vs pandas."""
    rng = np.random.default_rng(seed + 300)
    a = pd.DataFrame(
        {
            "k": rng.integers(0, keyspace, n).astype(np.int32),
            "v": (rng.normal(size=n) * 4).round(1).astype(np.float32),
        }
    )
    ta = ct.Table.from_pandas(ctx8, a)
    got = ta.distributed_groupby(
        "k", {"v": ["min", "max", "var", "std", "nunique", "median"]}
    ).to_pandas()
    got = got.set_index(got["k"].astype(np.int64)).sort_index()
    want = a.groupby("k")["v"].agg(
        ["min", "max", "var", "std", "nunique", "median"]
    ).sort_index()
    assert len(got) == len(want)
    for ours, theirs in (
        ("v_min", "min"), ("v_max", "max"), ("v_var", "var"),
        ("v_std", "std"), ("v_nunique", "nunique"), ("v_median", "median"),
    ):
        np.testing.assert_allclose(
            got[ours].to_numpy(np.float64),
            want[theirs].to_numpy(np.float64),
            rtol=1e-3, atol=1e-3, err_msg=ours, equal_nan=True,
        )


@pytest.mark.parametrize("seed,n,keyspace,dtype,null_p", CASES[:6])
def test_groupby_sum_mean_vs_pandas(ctx8, seed, n, keyspace, dtype, null_p):
    rng = np.random.default_rng(seed + 200)
    a = _rand_frame(rng, n, keyspace, dtype, null_p)
    ta = ct.Table.from_pandas(ctx8, a)
    got = ta.distributed_groupby("k", {"v": ["sum", "mean", "count"]}).to_pandas()
    want = a.groupby("k", dropna=True)["v"].agg(["sum", "mean", "count"])
    got = got.dropna(subset=["k"])

    def canon_key(s):
        # same folding as _norm: -0.0 onto 0.0, int/float reprs unified
        return s.map(
            lambda v: str(float(v) + 0.0)
            if isinstance(v, (int, float, np.integer, np.floating))
            and not isinstance(v, bool)
            else str(v)
        )

    got = got.assign(k=canon_key(got["k"])).set_index("k").sort_index()
    want.index = canon_key(want.index.to_series())
    want = want.sort_index()
    assert len(got) == len(want)
    np.testing.assert_allclose(got["v_sum"].to_numpy(), want["sum"].to_numpy(), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(got["v_mean"].to_numpy(), want["mean"].to_numpy(), rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(got["v_count"].to_numpy(), want["count"].to_numpy())
