"""Bit-width-adaptive lane packing tests (ISSUE 5).

Three layers, mirroring test_ordering.py:
  1. stats lifecycle — ColStat measurement (ensure_stats vs numpy),
     carriage through row-subset/rename ops, establishment by the shuffle
     count pass, and invalidation on in-place mutation;
  2. differential — every packed path (fused multi-key sort, fused
     groupby factorize, fused join probe, wire-narrowed shuffle) against
     the CYLON_TPU_NO_LANE_PACK=1 oracle at worlds {1, 2, 4, 8},
     including null masks, dictionary string keys, negative ints,
     descending keys, and f64 (which must decline);
  3. the pinned acceptance — the multi-key q3 pipeline (join ->
     groupby-SUM over two narrow int keys) runs >= 25% fewer traced
     sort-pass bytes at world 1, strictly fewer sort ops at world 4, with
     identical output.
"""
import os
import sys

import numpy as np
import pandas as pd
import pandas.testing as pdt
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import cylon_tpu as ct
from cylon_tpu.ops import stats as stmod
from cylon_tpu.ops.sort import plan_lane_fusion
from cylon_tpu.utils.tracing import get_count, reset_trace


@pytest.fixture(scope="module")
def ctx1(devices):
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:1]))


@pytest.fixture(scope="module")
def ctx4(devices):
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:4]))


def _norm(df):
    out = df.copy()
    for c in out.columns:
        if out[c].dtype == object:
            out[c] = out[c].map(lambda v: "\x00null" if v is None else str(v))
        else:
            out[c] = out[c].astype(np.float64)
    out = out.fillna(-1e30)
    return out.sort_values(list(out.columns), kind="mergesort").reset_index(
        drop=True
    )


def _assert_same(a, b):
    ap, bp = _norm(a.to_pandas()), _norm(b.to_pandas())
    pdt.assert_frame_equal(ap, bp)


# ----------------------------------------------------------------------
# 1. stats lifecycle
# ----------------------------------------------------------------------

def test_ensure_stats_bounds_match_numpy(ctx1, rng):
    n = 3000
    a = rng.integers(-500, 4000, n).astype(np.int32)
    t = ct.Table.from_pydict(ctx1, {
        "a": a,
        "f": rng.normal(size=n).astype(np.float64),
    })
    st = t.ensure_stats(["a", "f"])
    assert st["f"] is None  # f64 has no packable lane
    got = st["a"]
    assert got.cls == "i32"
    # orderable i32 encoding = value ^ 0x80000000 (sign flip)
    enc = (a.astype(np.int64) + 2**31).astype(np.uint64)
    assert got.lo == int(enc.min()) and got.hi == int(enc.max())
    # cached: second call returns the same object, no recompute
    assert t.ensure_stats(["a"])["a"] is got


def test_stats_measure_masked_values_too(ctx1, rng):
    """Null rows' PAYLOAD values ride sort lanes and wire fields, so the
    bounds must cover them — the stats ignore the validity mask."""
    n = 1000
    a = np.zeros(n, object)
    a[:] = 5
    a[0] = 999  # this row will be null, but its payload is still 999...
    df = pd.DataFrame({"a": a})
    df.loc[0, "a"] = None
    t = ct.Table.from_pandas(ctx1, df)
    # encode_host turns None into a masked fill value; whatever it is,
    # the measured span must cover every LIVE physical value
    st = t.ensure_stats(["a"])["a"]
    phys, _valid = t._host_physical("a")
    shift = 2**31 if st.cls == "i32" else 2**63
    enc = phys.astype(object) + shift  # object: no int64 overflow
    assert st.lo <= int(min(enc)) and st.hi >= int(max(enc))


def test_stats_carry_through_row_subsets(ctx1, rng):
    n = 2000
    t = ct.Table.from_pydict(ctx1, {
        "a": rng.integers(0, 100, n).astype(np.int32),
        "v": rng.normal(size=n).astype(np.float32),
    })
    st = t.ensure_stats(["a"])["a"]
    f = t.filter(t.column("a").data < 50)
    assert f._stats["a"] == st  # conservative bounds survive the subset
    s = t.sort("a")
    assert s._stats["a"] == st  # permutation
    r = t.rename({"a": "b"})
    assert r._stats["b"] == st  # descriptor follows its column
    p = t.project(["a"])
    assert p._stats["a"] == st


def test_shuffle_count_pass_establishes_stats(ctx4, rng):
    n = 4000
    t = ct.Table.from_pydict(ctx4, {
        "k": rng.integers(0, 300, n).astype(np.int32),
        "v": rng.normal(size=n).astype(np.float32),
    })
    assert not t._stats
    reset_trace()
    s = t.shuffle(["k"])
    # global bounds measured by the count kernel, attached to BOTH the
    # input (cache) and the output (values survive the reroute) with NO
    # dedicated stats kernel
    assert get_count("lane_pack.stats_kernel") == 0
    assert "k" in t._stats and "k" in s._stats
    assert t._stats["k"] == s._stats["k"]
    # ...so a downstream groupby pays no stats sync either
    reset_trace()
    s.groupby("k", {"v": "sum"})
    assert get_count("lane_pack.stats_kernel") == 0


def test_stats_invalidated_on_mutation(ctx1, rng):
    n = 1000
    t = ct.Table.from_pydict(ctx1, {
        "a": rng.integers(0, 50, n).astype(np.int32),
        "v": rng.normal(size=n).astype(np.float32),
    })
    t.ensure_stats(["a"])
    assert t._stats
    t["a"] = np.arange(n).astype(np.int32) * 100000  # in-place mutation
    assert not t._stats  # stale bounds must not drive a packing plan
    # re-measured stats reflect the NEW values, and the packed sort of the
    # mutated table matches the oracle (the regression this guards: a
    # stale 6-bit plan over the new 27-bit values would corrupt the order)
    st = t.ensure_stats(["a"])["a"]
    assert st.hi - st.lo >= 100000 * (n - 1)
    with stmod.disabled():
        want = t.sort(["a", "v"])
    _assert_same(t.sort(["a", "v"]), want)

    t2 = ct.Table.from_pydict(ctx1, {"a": np.arange(10, dtype=np.int32)})
    t2.ensure_stats(["a"])
    t2.dropna(inplace=True)
    t2["b"] = np.ones(10, np.float32)
    assert not t2._stats


# ----------------------------------------------------------------------
# 2. planner unit
# ----------------------------------------------------------------------

def test_plan_fuses_narrow_keys_into_one_word():
    # the ISSUE's headline shape: 12 + 16 + 20 bits -> ONE uint64 word
    specs = [("i32", 12, False, True), ("i32", 16, False, True),
             ("u32", 20, False, True)]
    plan = plan_lane_fusion(specs, pad_bits=2, prefix_bits=0, allow64=True)
    assert plan is not None and plan.n_words == 1 and plan.allow64
    assert plan.n_plain == 4  # 3 value lanes + pad
    # without x64 the same shape needs two uint32 words — still a win
    plan32 = plan_lane_fusion(specs, pad_bits=2, prefix_bits=0, allow64=False)
    assert plan32 is not None and plan32.n_words == 2 and not plan32.allow64


def test_plan_declines():
    # unknown stats on any key
    assert plan_lane_fusion(
        [("i32", 8, False, True), None], 2, 0, True
    ) is None
    # descending float (NaN-last pinning has no rebased-field encoding)
    assert plan_lane_fusion([("f32", 16, False, False)], 2, 0, True) is None
    # no strict gain: one full-width key is already one lane
    assert plan_lane_fusion([("i32", 32, False, True)], 2, 0, False) is None
    # a >32-bit field needs the single-uint64-word layout
    assert plan_lane_fusion([("i64", 40, False, True)], 2, 0, False) is None
    # null flags pack too: masked 32-bit key fuses 3 lanes -> 2 words
    p = plan_lane_fusion([("i32", 32, True, True)], 2, 0, False)
    assert p is not None and p.n_words == 2 and p.n_plain == 3


def test_bit_layout_round_trip(rng):
    """assemble_words/extract_fields invert each other for widths that
    straddle word boundaries and exceed 32 bits, and word-lex order
    equals field-lex order."""
    import jax.numpy as jnp

    bits = [2, 1, 12, 40, 17, 0, 30]  # pad, null, narrow, wide, straddlers
    n = 512
    fields = []
    for b in bits:
        hi = (1 << b) - 1
        v = rng.integers(0, hi + 1, n)
        fields.append(jnp.asarray(
            v.astype(np.uint64) if b > 32 else v.astype(np.uint32)
        ))
    for allow64 in (False, True):
        layout = stmod.layout_words(bits, allow64)
        words = stmod.assemble_words(fields, layout)
        got = stmod.extract_fields(words, layout, bits)
        for b, f, g in zip(bits, fields, got):
            assert np.array_equal(np.asarray(f), np.asarray(g)), (b, allow64)
        # order equivalence: tuple-compare the words (msb-first) vs fields
        wt = list(zip(*[np.asarray(w) for w in words]))
        ft = list(zip(*[np.asarray(f) for f in fields]))
        order_w = sorted(range(n), key=lambda i: (wt[i], i))
        order_f = sorted(range(n), key=lambda i: (ft[i], i))
        assert order_w == order_f, allow64


# ----------------------------------------------------------------------
# 3. differentials vs the CYLON_TPU_NO_LANE_PACK oracle
# ----------------------------------------------------------------------

def _mixed_frame(rng, n, null_p=0.15):
    k1 = rng.integers(-200, 1500, n).astype(np.int32).astype(object)
    if null_p:
        k1[rng.random(n) < null_p] = None
    return pd.DataFrame({
        "k1": k1,
        "k2": rng.choice([f"s{i}" for i in range(40)], n),
        "k3": (rng.integers(-50, 50, n) * 3).astype(np.int64),
        "v": rng.normal(size=n).astype(np.float32),
    })


@pytest.mark.parametrize("world", [1, 2, 4, 8])
def test_sort_packed_vs_oracle(world, devices, rng):
    ctx = ct.CylonContext.init_distributed(
        ct.TPUConfig(devices=devices[:world])
    )
    df = _mixed_frame(rng, 3000)
    t = ct.Table.from_pandas(ctx, df)
    reset_trace()
    got = t.sort(["k1", "k2", "k3"], ascending=[True, False, True])
    assert get_count("lane_pack.sort_fused") >= 1
    with stmod.disabled():
        t2 = ct.Table.from_pandas(ctx, df)
        want = t2.sort(["k1", "k2", "k3"], ascending=[True, False, True])
    pdt.assert_frame_equal(got.to_pandas(), want.to_pandas())


@pytest.mark.parametrize("world", [1, 2, 4, 8])
def test_join_groupby_packed_vs_oracle(world, devices, rng):
    ctx = ct.CylonContext.init_distributed(
        ct.TPUConfig(devices=devices[:world])
    )
    ldf = _mixed_frame(rng, 1500)
    rdf = _mixed_frame(rng, 1500).rename(columns={"v": "w"})
    lt, rt = ct.Table.from_pandas(ctx, ldf), ct.Table.from_pandas(ctx, rdf)
    j = lt.distributed_join(rt, on=["k1", "k2"], how="inner")
    g = j.distributed_groupby("k1_x", {"v": "sum"})
    with stmod.disabled():
        lt2 = ct.Table.from_pandas(ctx, ldf)
        rt2 = ct.Table.from_pandas(ctx, rdf)
        jw = lt2.distributed_join(rt2, on=["k1", "k2"], how="inner")
        gw = jw.distributed_groupby("k1_x", {"v": "sum"})
    _assert_same(j, jw)
    _assert_same(g, gw)


def test_f64_key_declines_but_matches(ctx1, rng):
    n = 1200
    df = pd.DataFrame({
        "a": rng.integers(0, 40, n).astype(np.int32),
        "f": rng.normal(size=n).astype(np.float64),
    })
    t = ct.Table.from_pandas(ctx1, df)
    reset_trace()
    got = t.sort(["a", "f"])
    assert get_count("lane_pack.sort_fused") == 0  # f64 must decline
    with stmod.disabled():
        want = ct.Table.from_pandas(ctx1, df).sort(["a", "f"])
    pdt.assert_frame_equal(got.to_pandas(), want.to_pandas())


@pytest.mark.parametrize("world", [2, 4, 8])
def test_wire_narrowed_shuffle_vs_oracle(world, devices, rng):
    """The stats-driven wire codec ships narrow ints + 1-bit masks and the
    received table is identical to the plain int32-lane exchange."""
    ctx = ct.CylonContext.init_distributed(
        ct.TPUConfig(devices=devices[:world])
    )
    df = _mixed_frame(rng, 4000)
    t = ct.Table.from_pandas(ctx, df)
    reset_trace()
    got = t.shuffle(["k1"])
    assert get_count("lane_pack.wire.applied") >= 1
    with stmod.disabled():
        t2 = ct.Table.from_pandas(ctx, df)
        want = t2.shuffle(["k1"])
    assert (got.row_counts == want.row_counts).all()
    _assert_same(got, want)


def test_wire_gate_declines_without_gain(ctx4, rng):
    """Full-width mask-free floats leave nothing to narrow: the wire plan
    is absent (not merely unprofitable) and the plain codec runs."""
    n = 3000
    t = ct.Table.from_pydict(ctx4, {
        "k": (rng.normal(size=n) * 1e6).astype(np.float32),
        "v": rng.normal(size=n).astype(np.float32),
    })
    reset_trace()
    t.shuffle(["k"])
    assert get_count("lane_pack.wire.applied") == 0


def test_setops_and_unique_packed_vs_oracle(ctx4, rng):
    df1 = _mixed_frame(rng, 1200)[["k1", "k3"]]
    df2 = _mixed_frame(rng, 1200)[["k1", "k3"]]
    a, b = ct.Table.from_pandas(ctx4, df1), ct.Table.from_pandas(ctx4, df2)
    got_i = a.distributed_intersect(b)
    got_u = a.distributed_unique(["k1"])
    with stmod.disabled():
        a2 = ct.Table.from_pandas(ctx4, df1)
        b2 = ct.Table.from_pandas(ctx4, df2)
        want_i = a2.distributed_intersect(b2)
        want_u = a2.distributed_unique(["k1"])
    _assert_same(got_i, want_i)
    _assert_same(got_u, want_u)


def test_kill_switch_silences_everything(ctx4, rng):
    df = _mixed_frame(rng, 1500)
    with stmod.disabled():
        t = ct.Table.from_pandas(ctx4, df)
        reset_trace()
        t.sort(["k1", "k3"])
        t.shuffle(["k1"])
        t.groupby("k1", {"v": "sum"})
        assert t.ensure_stats(["k1"]) == {}
        for c in ("lane_pack.sort_fused", "lane_pack.groupby_fused",
                  "lane_pack.join_fused", "lane_pack.wire.applied",
                  "lane_pack.stats_kernel"):
            assert get_count(c) == 0, c


# ----------------------------------------------------------------------
# 4. plan layer
# ----------------------------------------------------------------------

def test_explain_annotates_stats_and_fingerprint_tracks_gate(ctx1, rng):
    n = 1000
    t = ct.Table.from_pydict(ctx1, {
        "k": rng.integers(0, 100, n).astype(np.int32),
        "v": rng.normal(size=n).astype(np.float32),
    })
    t.ensure_stats(["k"])
    lf = t.lazy().groupby("k", {"v": "sum"})
    txt = lf.explain()
    assert "-- stats:" in txt and "k:" in txt
    # the kill switch is part of the plan-executable identity: flipping it
    # must re-optimize (a cache miss), never reuse the packed executor
    from cylon_tpu.utils.tracing import get_count as gc

    lf.collect()
    before = gc("plan.cache.miss")
    with stmod.disabled():
        lf.collect()
    assert gc("plan.cache.miss") == before + 1


# ----------------------------------------------------------------------
# 5. the pinned q3 acceptance gate
# ----------------------------------------------------------------------

def _sort_totals(op):
    from benchmarks.roofline import Report, analyze
    from cylon_tpu import engine

    op()  # warm
    engine.record_kernels(True)
    try:
        op()
    finally:
        kernels = engine.recorded_kernels()
        engine.record_kernels(False)
    total = Report()
    for fn, args in kernels:
        rep = analyze(fn, *args)
        total.sort_count += rep.sort_count
        total.sort_pass_bytes += rep.sort_pass_bytes
        total.collective_bytes += rep.collective_bytes
    return total


@pytest.mark.parametrize("world", [1, 4])
def test_q3_sort_gb_reduction(world, devices):
    """Acceptance: the multi-key narrow-lane q3 pipeline (inner join on
    two int keys spanning ~12 and ~16 bits -> groupby-SUM) through lane
    packing runs with >= 25% fewer traced sort-pass bytes at world 1
    (where the relational sorts are the whole cost) and strictly fewer
    sort ops + no sort-byte regression at world 4 (where the shuffle
    engine's compaction argsorts dilute the ratio), with identical
    output and no collective-byte regression."""
    ctx = ct.CylonContext.init_distributed(
        ct.TPUConfig(devices=devices[:world])
    )
    rng = np.random.default_rng(16)
    n = 20000
    lt = ct.Table.from_pydict(ctx, {
        "k1": rng.integers(0, 4000, n).astype(np.int32),
        "k2": rng.integers(0, 60000, n).astype(np.int32),
        "v": rng.normal(size=n).astype(np.float32),
    })
    rt = ct.Table.from_pydict(ctx, {
        "k1": rng.integers(0, 4000, n).astype(np.int32),
        "k2": rng.integers(0, 60000, n).astype(np.int32),
        "w": rng.normal(size=n).astype(np.float32),
    })
    res = {}

    def q3(tag):
        def run():
            res[tag] = lt.distributed_join(
                rt, on=["k1", "k2"], how="inner"
            ).distributed_groupby(["k1_x", "k2_x"], {"v": "sum"})

        return run

    # pin the BITONIC engine for both runs: packing's sort-byte gain is
    # a sweep-count claim (fewer sort words -> fewer L(L+1)/2 networks),
    # which only the comparison sort exhibits — the radix engine prices
    # passes by total significant bits, which packing leaves unchanged
    # (its gate lives in tools/sort_smoke.py instead)
    from cylon_tpu.ops import radix as rx
    with rx.disabled():
        tp = _sort_totals(q3("packed"))
        with stmod.disabled():
            tu = _sort_totals(q3("oracle"))
    assert tp.sort_count < tu.sort_count
    assert tp.collective_bytes <= tu.collective_bytes
    reduction = 1.0 - tp.sort_pass_bytes / tu.sort_pass_bytes
    floor = 0.25 if world == 1 else 0.0
    assert reduction >= floor, (
        f"sort-pass bytes only reduced {reduction:.1%} at world={world} "
        f"({tu.sort_pass_bytes / 1e9:.3f} -> {tp.sort_pass_bytes / 1e9:.3f} GB)"
    )
    _assert_same(res["packed"], res["oracle"])
