"""Per-shard ingestion / per-rank IO / typed Arrow interop tests
(VERDICT round-1 items 3, 6, 9).

The reference's ingest model is each MPI rank reading only its partition
(table.cpp:791-829); the round-1 repo materialized the whole global table in
one host buffer first. These tests pin the O(one shard) staging behavior,
the per-rank write paths, the typed (no-pandas) Arrow bridge, and the
device-side take/equals.
"""
import os
import tracemalloc

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.io.parquet import read_parquet, write_parquet


def test_from_shards_no_global_buffer(devices):
    """Peak host allocation during per-shard ingest stays O(one shard), not
    O(global table): 8 shards x 4 MB must not allocate a ~32 MB buffer."""
    ctx = ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:8]))
    n_per = 500_000  # 4 MB per shard as int64
    shards = [
        {"v": np.arange(i * n_per, (i + 1) * n_per, dtype=np.int64)}
        for i in range(8)
    ]
    tracemalloc.start()
    t = ct.Table.from_shards(ctx, shards)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    global_bytes = 8 * n_per * 8
    assert peak < global_bytes / 2, f"peak host alloc {peak} ~ global {global_bytes}"
    assert t.row_count == 8 * n_per
    assert t.row_counts.tolist() == [n_per] * 8
    # content spot check per shard
    assert int(t.min("v")) == 0 and int(t.max("v")) == 8 * n_per - 1


def test_from_shards_string_dictionary_unify(devices):
    """Per-shard encoding with per-shard dictionaries must still rendezvous
    equal strings (cross-shard dictionary union)."""
    ctx = ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:4]))
    shards = [
        {"s": np.array(["b", "a"] * 3), "v": np.arange(6)},
        {"s": np.array(["c", "b"] * 3), "v": np.arange(6)},
        {"s": np.array(["a", "d"] * 3), "v": np.arange(6)},
        {"s": np.array(["d", "c"] * 3), "v": np.arange(6)},
    ]
    t = ct.Table.from_shards(ctx, shards)
    g = t.distributed_groupby("s", {"v": "count"})
    gp = g.to_pandas().sort_values("s").reset_index(drop=True)
    assert gp["s"].tolist() == ["a", "b", "c", "d"]
    assert gp["v_count"].tolist() == [6, 6, 6, 6]


def test_per_rank_csv_write_read_roundtrip(devices, tmp_path, rng):
    ctx = ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:4]))
    n = 1000
    t = ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(0, 50, n).astype(np.int64),
         "v": rng.normal(size=n),
         "s": np.array([f"name_{i % 7}" for i in range(n)])},
    )
    paths = [str(tmp_path / f"part_{i}.csv") for i in range(4)]
    ct.write_csv(t, paths)
    for i, p in enumerate(paths):
        assert os.path.exists(p)
        assert len(pd.read_csv(p)) == t.row_counts[i]
    back = ct.read_csv(ctx, paths)
    assert back.row_counts.tolist() == t.row_counts.tolist()
    a = t.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    b = back.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b, check_dtype=False, rtol=1e-12)


def test_per_rank_parquet_write_read_roundtrip(devices, tmp_path, rng):
    ctx = ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:4]))
    n = 800
    vals = rng.normal(size=n)
    vals[::13] = np.nan  # nulls survive parquet round trip
    t = ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(0, 50, n).astype(np.int32),
         "v": vals,
         "s": np.array([f"s{i % 5}" for i in range(n)])},
    )
    paths = [str(tmp_path / f"part_{i}.parquet") for i in range(4)]
    write_parquet(t, paths)
    back = read_parquet(ctx, paths)
    assert back.row_counts.tolist() == t.row_counts.tolist()
    a = t.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    b = back.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b, check_dtype=False, rtol=1e-12)


def test_typed_arrow_roundtrip(devices):
    """to_arrow/from_arrow keep types: int64 with nulls stays integral
    (pandas bounce would float64 it), dictionary columns export codes."""
    import pyarrow as pa

    ctx = ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:2]))
    at = pa.table(
        {
            "i": pa.array([1, None, 3, 4], type=pa.int64()),
            "f": pa.array([1.5, 2.5, None, 4.5]),
            "s": pa.array(["x", "y", None, "x"]),
            "b": pa.array([True, False, True, None]),
        }
    )
    t = ct.Table.from_arrow(ctx, at)
    assert t.column("i").dtype.is_numeric and not t.column("i").dtype.is_floating
    back = t.to_arrow()
    assert back.column("i").type == pa.int64()
    assert pa.types.is_dictionary(back.column("s").type)
    assert back.column("i").null_count == 1
    assert back.column("s").null_count == 1
    assert back.column("i").to_pylist() == [1, None, 3, 4]
    assert back.column("s").to_pylist() == ["x", "y", None, "x"]
    assert back.column("b").to_pylist() == [True, False, True, None]


def test_take_device_gather(devices, rng):
    ctx = ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:4]))
    n = 400
    v = rng.normal(size=n)
    s = np.array([f"r{i % 9}" for i in range(n)])
    t = ct.Table.from_pydict(ctx, {"v": v, "s": s})
    idx = rng.permutation(n)[:123]
    got = t.take(idx).to_pandas()
    exp = pd.DataFrame({"v": v, "s": s}).iloc[idx].reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)
    # negative indices wrap like numpy
    got2 = t.take([-1, 0]).to_pandas()
    assert got2["v"].tolist() == [v[-1], v[0]]
    with pytest.raises(IndexError):
        t.take([n])


def test_equals_device_paths(devices, rng):
    ctx = ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:4]))
    n = 300
    k = rng.integers(0, 20, n).astype(np.int32)
    v = rng.normal(size=n)
    t1 = ct.Table.from_pydict(ctx, {"k": k, "v": v})
    t2 = ct.Table.from_pydict(ctx, {"k": k.copy(), "v": v.copy()})
    assert t1.equals(t2)
    # same multiset, different order
    perm = rng.permutation(n)
    t3 = ct.Table.from_pydict(ctx, {"k": k[perm], "v": v[perm]})
    assert not t1.equals(t3)
    assert t1.equals(t3, ordered=False)
    # wrong multiplicities must fail the unordered compare: duplicate one
    # row, drop another occurrence of a different row
    kk, vv = k.copy(), v.copy()
    kk[0], vv[0] = kk[1], vv[1]
    t4 = ct.Table.from_pydict(ctx, {"k": kk, "v": vv})
    assert not t1.equals(t4, ordered=False)


def test_equals_with_nulls(devices, rng):
    ctx = ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:2]))
    v = np.array([1.0, np.nan, 3.0, np.nan])
    t1 = ct.Table.from_pydict(ctx, {"v": v})
    t2 = ct.Table.from_pydict(ctx, {"v": v.copy()})
    assert t1.equals(t2)
    assert t1.equals(t2, ordered=False)
    t3 = ct.Table.from_pydict(ctx, {"v": np.array([1.0, np.nan, 4.0, np.nan])})
    assert not t1.equals(t3)
    assert not t1.equals(t3, ordered=False)
