"""The concurrent query-serving engine (ISSUE 9).

Four pinned properties:

- DIFFERENTIAL: a batch of B same-fingerprint bindings executed as ONE
  stacked device program must produce, per binding, exactly the rows the
  serial ``collect()`` of that binding produces — across worlds {1,4,8},
  int and dictionary-encoded string keys, nulls, and every batchable
  tail (fused q3 groupby-sum, multi-agg, sort, project, left/right
  joins). Values are integer-valued f32 so sums are order-exact and the
  comparison is EQUALITY, not tolerance.
- ADMISSION: under a tight in-flight byte budget, N threads hammering
  ``collect_async`` must backpressure (submitters wait) and still lose
  or duplicate NOTHING; the shed path raises ServeOverloadError without
  touching admitted work.
- CACHE: B bindings compile exactly one batched executor per
  (fingerprint, pow2-B-bucket) — the serve tier's compile-once pin.
- HOT-LOOP HASHING (ISSUE 9 small fix): repeated cached collects perform
  ZERO fingerprint_key hashes — the key is hoisted onto the cached
  executor entry (``engine.PlanEntry.hist_key``).
"""
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu import col
from cylon_tpu.obs import metrics as obs_metrics
from cylon_tpu.serve import (
    QueryFuture,
    ServeOverloadError,
    ServeScheduler,
    estimate_query_bytes,
    is_batchable,
)
from cylon_tpu.utils import tracing


@pytest.fixture(scope="module", params=[1, 4, 8])
def serve_ctx(request, devices):
    return ct.CylonContext.init_distributed(
        ct.TPUConfig(devices=devices[: request.param])
    )


@pytest.fixture(scope="module")
def sctx4(devices):
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:4]))


def _mk_binding(ctx, rng, n, str_keys=False, nulls=False):
    """One (left, right) parameter binding. Values are integer-valued
    float32 so reduction order cannot perturb sums (exact equality)."""
    if str_keys:
        k = rng.choice([f"s{i}" for i in range(12)], n).astype(object)
        rk = rng.choice([f"s{i}" for i in range(15)], n).astype(object)
        if nulls:
            k[rng.random(n) < 0.1] = None
    else:
        k = rng.integers(0, 20, n).astype(np.int32)
        rk = rng.integers(0, 20, n).astype(np.int32)
    ta = ct.Table.from_pydict(
        ctx, {"k": k, "v": rng.integers(-50, 50, n).astype(np.float32)}
    )
    tb = ct.Table.from_pydict(
        ctx, {"rk": rk, "w": rng.integers(-50, 50, n).astype(np.float32)}
    )
    return ta, tb


def _q3(ta, tb):
    return (
        ta.lazy()
        .join(tb.lazy(), left_on="k", right_on="rk")
        .filter(col("w") > 0.0)
        .groupby("k", {"v": "sum"})
    )


def _canon(pydict):
    """Canonical row order + null normalization: batched execution
    guarantees the exact row SET (and per-query sort-key order), not the
    serial shard-concatenation order — equal key tuples may hash to
    different shards once the binding id joins the key."""
    df = pd.DataFrame(pydict)
    for c in df.columns:
        if df[c].dtype == object:
            df[c] = df[c].map(lambda v: "\0null" if v is None else str(v))
    df = df.fillna("\0null").astype(str)
    return df.sort_values(list(df.columns)).reset_index(drop=True)


def _assert_same(got, want, label=""):
    assert list(got) == list(want), (label, list(got), list(want))
    pd.testing.assert_frame_equal(
        _canon(got), _canon(want), check_dtype=False, obj=label or "result"
    )


def _run_batched(ctx, plans):
    s = ServeScheduler(ctx, auto_start=False)
    futs = [s.submit(p) for p in plans]
    s.run_pending()
    return [f.result(timeout=120) for f in futs]


# ----------------------------------------------------------------------
# batched-vs-serial exact differential, worlds {1, 4, 8}
# ----------------------------------------------------------------------
def test_batched_equals_serial_q3(serve_ctx, rng):
    plans = [
        _q3(*_mk_binding(serve_ctx, rng, 150 + 37 * i)) for i in range(5)
    ]
    oracle = [p.collect().to_pydict() for p in plans]
    before = tracing.get_count("serve.batch_cache.miss")
    got = _run_batched(serve_ctx, plans)
    assert tracing.get_count("serve.batches") >= 1
    assert tracing.get_count("serve.batch_cache.miss") == before + 1
    for i, t in enumerate(got):
        _assert_same(t.to_pydict(), oracle[i], f"q3 binding {i}")


def test_batched_equals_serial_string_nulls(serve_ctx, rng):
    """Dictionary-encoded keys with per-binding dictionaries: stacking
    must unify them (codes remapped against the union dictionary)."""
    plans = [
        _q3(*_mk_binding(serve_ctx, rng, 120 + 29 * i, str_keys=True,
                         nulls=True))
        for i in range(4)
    ]
    oracle = [p.collect().to_pydict() for p in plans]
    for i, t in enumerate(_run_batched(serve_ctx, plans)):
        _assert_same(t.to_pydict(), oracle[i], f"string binding {i}")


def test_batched_equals_serial_tails(serve_ctx, rng):
    """Non-q3 batchable shapes: sort tail, left-join + project,
    right join, multi-aggregate groupby."""
    mk = lambda i: _mk_binding(serve_ctx, rng, 100 + 13 * i)  # noqa: E731
    shapes = {
        "sort": lambda ta, tb: ta.lazy()
        .join(tb.lazy(), left_on="k", right_on="rk")
        .sort(["k", "v"]),
        "left-project": lambda ta, tb: ta.lazy()
        .join(tb.lazy(), left_on="k", right_on="rk", how="left")
        .select(["k", "w"]),
        "right": lambda ta, tb: ta.lazy().join(
            tb.lazy(), left_on="k", right_on="rk", how="right"
        ),
        "multi-agg": lambda ta, tb: ta.lazy()
        .filter(col("v") > 0.0)
        .groupby("k", {"v": ["min", "count", "mean"]}),
    }
    for name, build in shapes.items():
        plans = [build(*mk(i)) for i in range(3)]
        oracle = [p.collect().to_pydict() for p in plans]
        for i, t in enumerate(_run_batched(serve_ctx, plans)):
            got = t.to_pydict()
            _assert_same(got, oracle[i], f"{name} binding {i}")
            if name == "sort":
                # RAW order, not just the canonicalized set: each
                # binding's slice must come out in its requested sort
                # order (qid-leading batched sort + stable split)
                order = np.lexsort(
                    (np.asarray(got["v"]), np.asarray(got["k"]))
                )
                assert np.array_equal(
                    order, np.arange(len(got["k"]))
                ), f"sort binding {i} rows not in (k, v) order"


def test_unbatchable_limit_falls_back_to_singles(sctx4, rng):
    ta, _ = _mk_binding(sctx4, rng, 80)
    lf = ta.lazy().sort("k").limit(7)
    assert not is_batchable(lf.plan)
    before = tracing.get_count("serve.singles")
    s = ServeScheduler(sctx4, auto_start=False)
    futs = [s.submit(lf), s.submit(ta.lazy().sort("k").limit(7))]
    s.run_pending()
    want = lf.collect().to_pydict()
    for f in futs:
        _assert_same(f.result(timeout=60).to_pydict(), want, "limit")
    assert tracing.get_count("serve.singles") == before + 2


def test_dataframe_collect_async_roundtrip(sctx4, rng):
    df = ct.DataFrame(
        {"a": np.arange(40, dtype=np.int64),
         "b": rng.integers(0, 9, 40).astype(np.int32)},
        ctx=sctx4,
    )
    fut = df.collect_async()
    assert isinstance(fut, QueryFuture)
    out = fut.result(timeout=60)
    assert isinstance(out, ct.DataFrame)
    _assert_same(out.to_table().to_pydict(), df.to_table().to_pydict())


# ----------------------------------------------------------------------
# admission control: backpressure + shed
# ----------------------------------------------------------------------
def test_hammer_backpressure_zero_lost(sctx4, rng, monkeypatch):
    """16 threads, each submitting AND consuming its own distinct
    binding (the concurrent-serving pattern) through a worker scheduler
    whose in-flight budget admits ~3 unconsumed queries: submitters must
    WAIT (the backpressure queue engages while the drain is frozen), a
    shed — possible if consumption momentarily lags past the 2x hard
    cap — is retried like a real client, and every query resolves
    exactly once to its own binding's serial result."""
    bindings = [_mk_binding(sctx4, rng, 120 + 7 * i) for i in range(16)]
    plans = [_q3(ta, tb) for ta, tb in bindings]
    oracle = [p.collect().to_pydict() for p in plans]
    est = estimate_query_bytes(
        [bindings[0][0], bindings[0][1]]
    )
    monkeypatch.setenv("CYLON_TPU_SERVE_INFLIGHT_BYTES", str(3 * est))
    wait_before = tracing.get_count("serve.backpressure.wait")
    s = ServeScheduler(sctx4, auto_start=True)
    s.pause()  # freeze the drain: the first wave MUST backpressure
    barrier = threading.Barrier(16)

    def worker(i):
        barrier.wait()
        while True:
            try:
                fut = s.submit(plans[i])
                break
            except ServeOverloadError:
                time.sleep(0.005)  # shed: back off and retry
        return i, fut.result(timeout=120).to_pydict()

    with ThreadPoolExecutor(max_workers=16) as ex:
        pending = [ex.submit(worker, i) for i in range(16)]
        # with the drain frozen the budget admits ~3 queries, so the
        # other submitters are provably waiting: poll the counter, THEN
        # release the drain
        deadline = time.monotonic() + 30
        while (
            tracing.get_count("serve.backpressure.wait") == wait_before
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert tracing.get_count("serve.backpressure.wait") > wait_before
        s.resume()
        results = dict(p.result(timeout=180) for p in pending)
    assert len(results) == 16
    for i in range(16):
        _assert_same(results[i], oracle[i], f"hammer binding {i}")
    assert s.drain(timeout=30)
    assert s.stats()["inflight_bytes"] == 0  # everything consumed
    s.close()


def test_shed_error_contract(sctx4, rng, monkeypatch):
    ta, tb = _mk_binding(sctx4, rng, 100)
    lf = _q3(ta, tb)
    # sheds count by REASON (serve.shed.*), so the SLO rules and an
    # autoscaler can tell offered load from a consumer leak
    budget_before = tracing.get_count("serve.shed.admission_budget")
    queue_before = tracing.get_count("serve.shed.queue_depth")

    # (a) a query whose estimate alone exceeds the hard cap sheds at
    # submit, blocking or not
    monkeypatch.setenv("CYLON_TPU_SERVE_INFLIGHT_BYTES", "1")
    s = ServeScheduler(sctx4, auto_start=False)
    with pytest.raises(ServeOverloadError):
        s.submit(lf)
    assert (
        tracing.get_count("serve.shed.admission_budget") == budget_before + 1
    )
    monkeypatch.delenv("CYLON_TPU_SERVE_INFLIGHT_BYTES")

    # (b) a full queue sheds nowait submitters and loses nothing admitted
    monkeypatch.setenv("CYLON_TPU_SERVE_QUEUE_DEPTH", "2")
    f1 = s.submit(lf)
    f2 = s.submit(_q3(*_mk_binding(sctx4, rng, 90)))
    with pytest.raises(ServeOverloadError):
        s.submit(_q3(*_mk_binding(sctx4, rng, 80)), block=False)
    assert tracing.get_count("serve.shed.queue_depth") == queue_before + 1
    s.run_pending()
    assert f1.result(timeout=60).row_count == lf.collect().row_count
    assert f2.exception(timeout=60) is None


def test_inflight_lease_released_on_consumption(sctx4, rng):
    """The byte budget covers fulfilled-but-unread results: leases stay
    held after dispatch, release on result() consumption, and release
    via the GC finalizer when an unconsumed future is dropped."""
    s = ServeScheduler(sctx4, auto_start=False)
    futs = [s.submit(_q3(*_mk_binding(sctx4, rng, 70))) for _ in range(3)]
    held = s.stats()["inflight_bytes"]
    assert held > 0
    s.run_pending()
    assert all(f.done() for f in futs)
    # fulfilled != consumed: leases stay held, and batched dispatch adds
    # the split-burst surcharge so admission sees the slices' footprint
    assert s.stats()["inflight_bytes"] >= held
    for f in futs:
        f.result(timeout=60)
    assert s.stats()["inflight_bytes"] == 0
    fut = s.submit(_q3(*_mk_binding(sctx4, rng, 60)))
    s.run_pending()
    assert s.stats()["inflight_bytes"] > 0
    del fut  # dropped unconsumed: the finalizer returns the lease
    import gc

    gc.collect()
    assert s.stats()["inflight_bytes"] == 0


# ----------------------------------------------------------------------
# compile-once pins
# ----------------------------------------------------------------------
def test_batch_cache_one_compile_per_bucket(sctx4, rng, monkeypatch):
    """B bindings -> exactly 1 batched-executor compile per (fingerprint,
    pow2 B bucket); re-serving the same shape at the same bucket is a
    pure cache hit."""
    monkeypatch.setenv("CYLON_TPU_SERVE_BATCH_MAX", "8")
    # a literal no other test uses: a fresh fingerprint
    build = lambda ta, tb: (  # noqa: E731
        ta.lazy()
        .join(tb.lazy(), left_on="k", right_on="rk")
        .filter(col("w") > 0.3216549)
        .groupby("k", {"v": "sum"})
    )
    bindings = [_mk_binding(sctx4, rng, 90 + 5 * i) for i in range(8)]
    miss0 = tracing.get_count("serve.batch_cache.miss")
    hit0 = tracing.get_count("serve.batch_cache.hit")
    s = ServeScheduler(sctx4, auto_start=False)

    def serve_all(n):
        futs = [s.submit(build(ta, tb)) for ta, tb in bindings[:n]]
        s.run_pending()
        return [f.result(timeout=120) for f in futs]

    serve_all(8)  # bucket 8: compile
    assert tracing.get_count("serve.batch_cache.miss") == miss0 + 1
    serve_all(8)  # bucket 8 again: hit
    assert tracing.get_count("serve.batch_cache.miss") == miss0 + 1
    assert tracing.get_count("serve.batch_cache.hit") == hit0 + 1
    serve_all(3)  # bucket 4 (pow2-padded): one new compile
    assert tracing.get_count("serve.batch_cache.miss") == miss0 + 2


def test_cached_collect_zero_fingerprint_hashes(sctx4, rng):
    """The ISSUE-9 small fix: the histogram key is hoisted onto the
    cached executor entry, so the serving hot loop re-derives NOTHING —
    plan.fingerprint.hash stays flat across cached collects (it used to
    grow by one per collect), while the latency histogram keeps filling
    under the hoisted key."""
    lf = _q3(*_mk_binding(sctx4, rng, 130))
    lf.collect()  # compile: hashes once, onto the entry
    hist_key = lf._executable()[2].hist_key
    q_before = obs_metrics.latency_quantiles(hist_key)["count"]
    before = tracing.get_count("plan.fingerprint.hash")
    for _ in range(5):
        lf.collect()
    assert tracing.get_count("plan.fingerprint.hash") == before
    assert obs_metrics.latency_quantiles(hist_key)["count"] == q_before + 5
