"""Spill-tiered shuffle (ISSUE 10): differential coverage of the unified
budget-driven round planner across all three tiers.

Tier 0 (in-HBM rounds) is the oracle; tiers 1 (host-RAM arenas) and 2
(disk-backed arenas) must produce identical results for every
``Distributed*`` op while streaming their rounds through
``parallel/spill.py``. Skew profiles (one-hot + Zipf) cross the tiers
with the chunked K sweep; the forced-tier env knobs and the plan
fingerprint's tier component are pinned here too.
"""
import contextlib
import os

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.parallel import shuffle as _sh
from cylon_tpu.parallel import spill as _sp
from cylon_tpu.utils.tracing import report, reset_trace


@contextlib.contextmanager
def _env(**kv):
    prev = {k: os.environ.get(k) for k in kv}
    for k, v in kv.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = str(v)
    try:
        yield
    finally:
        for k, p in prev.items():
            if p is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = p


_CTXS = {}


def _ctx(devices, world):
    # one context (== one kernel cache) per mesh size for the whole
    # module: fresh contexts would recompile every engine kernel per test
    if world not in _CTXS:
        _CTXS[world] = ct.CylonContext.init_distributed(
            ct.TPUConfig(devices=devices[:world])
        )
    return _CTXS[world]


def _frames(seed, n=3000, keyspace=400):
    rng = np.random.default_rng(seed)
    ldf = pd.DataFrame(
        {"k": rng.integers(0, keyspace, n).astype(np.int32),
         "v": rng.normal(size=n).astype(np.float32)}
    )
    rdf = pd.DataFrame(
        {"k": rng.integers(0, keyspace, n // 2).astype(np.int32),
         "w": rng.normal(size=n // 2).astype(np.float32)}
    )
    return ldf, rdf


def _sorted(df, cols):
    return df.sort_values(cols, kind="mergesort").reset_index(drop=True)


_ORACLES = {}


@pytest.mark.parametrize("world", [1, 4, 8])
@pytest.mark.parametrize("tier", [1, 2])
def test_forced_tier_ops_match_in_core_oracle(devices, world, tier):
    """join / sort / union / subtract / intersect under a FORCED spill
    tier equal the tier-0 in-core oracle bit-for-bit (worlds 1/4/8)."""
    ctx = _ctx(devices, world)
    ldf, rdf = _frames(17 + world)
    lt = ct.Table.from_pydict(ctx, {c: ldf[c].to_numpy() for c in ldf})
    rt = ct.Table.from_pydict(ctx, {c: rdf[c].to_numpy() for c in rdf})
    lt2 = ct.Table.from_pydict(
        ctx, {"k": ldf["k"].to_numpy(), "v": (ldf["v"] * 2).to_numpy()}
    )

    def run_all():
        out = {}
        out["join"] = _sorted(
            lt.distributed_join(rt, on="k", how="inner").to_pandas(),
            ["k_x", "v", "w"],
        )
        out["sort"] = lt.distributed_sort("k").to_pandas()["k"].to_numpy()
        out["union"] = _sorted(
            lt.distributed_union(lt2).to_pandas(), ["k", "v"]
        )
        out["subtract"] = _sorted(
            lt.distributed_subtract(lt2).to_pandas(), ["k", "v"]
        )
        out["intersect"] = _sorted(
            lt.distributed_intersect(lt).to_pandas(), ["k", "v"]
        )
        return out

    # tier 0 oracle, computed once per world (both tier params compare
    # against the same in-core result)
    base = _ORACLES.get(world)
    if base is None:
        base = _ORACLES[world] = run_all()
    with _env(CYLON_TPU_SPILL_TIER=tier):
        reset_trace()
        got = run_all()
        r = report("shuffle.spill.")
        if world > 1:
            assert r["shuffle.spill.shuffles"]["count"] >= 1
            assert r["shuffle.spill.staged_rounds"]["count"] >= 1
            assert r["shuffle.spill.tier"]["max_s"] == tier
    for name in base:
        if name == "sort":
            assert np.array_equal(base[name], got[name]), name
        else:
            pd.testing.assert_frame_equal(
                base[name], got[name], check_dtype=False
            )
    # sanity vs pandas for the join
    expect = ldf.merge(rdf, on="k", how="inner")
    assert len(base["join"]) == len(expect)


def _budget_for(t, max_bucket, k):
    return _sh.budget_for_rounds(
        max_bucket, k, t.world_size, _sh.exchange_row_bytes(t._flat_cols())
    )


@pytest.mark.parametrize("k", [1, 4, 16])
@pytest.mark.parametrize("profile", ["one_hot", "zipf"])
def test_spilled_skew_profiles_match_oracle(devices, k, profile):
    """One-hot + Zipf skew at K in {1, 4, 16} chunked rounds, forced
    through tier 1: spilled + skew-split results equal the in-core
    unchunked shuffle row-for-row."""
    ctx = _ctx(devices, 8)
    n, world = 4096, 8
    rng = np.random.default_rng(23 + k)
    if profile == "one_hot":
        keys = np.zeros(n, np.int32)
        max_bucket = n // world
    else:
        keys = (rng.zipf(1.3, n) % 131).astype(np.int32)
        max_bucket = int(
            np.bincount(keys % world, minlength=world).max()
        ) // world + 1
    t = ct.Table.from_pydict(
        ctx, {"k": keys, "v": rng.normal(size=n).astype(np.float32)}
    )
    budget = _budget_for(t, max_bucket, k)
    base = t.shuffle(["k"], byte_budget=1 << 40)  # in-core oracle
    with _env(CYLON_TPU_SPILL_TIER=1):
        reset_trace()
        s = t.shuffle(["k"], byte_budget=budget)
        assert report("shuffle.spill.")[
            "shuffle.spill.staged_rounds"
        ]["count"] >= 1
    assert s.row_count == n
    assert (s.row_counts == base.row_counts).all()
    sp = _sorted(s.to_pandas(), ["k", "v"])
    bp = _sorted(base.to_pandas(), ["k", "v"])
    assert np.array_equal(sp["k"].to_numpy(), bp["k"].to_numpy())
    assert np.allclose(sp["v"].to_numpy(), bp["v"].to_numpy())


def test_auto_tier_from_measured_counts(devices):
    """The tier decision is measured, not static: a tiny device spill
    budget flips the SAME shuffle from tier 0 to tier 1 with no forced
    knob, and results stay identical."""
    ctx = _ctx(devices, 8)
    rng = np.random.default_rng(5)
    t = ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(0, 500, 4000).astype(np.int32),
         "v": rng.normal(size=4000).astype(np.float32)},
    )
    base = t.shuffle(["k"])
    reset_trace()
    with _env(CYLON_TPU_SPILL_DEVICE_BUDGET=64):
        s = t.shuffle(["k"])
        assert report("shuffle.spill.")[
            "shuffle.spill.shuffles"
        ]["count"] == 1
    assert (s.row_counts == base.row_counts).all()
    assert np.array_equal(
        np.sort(s.to_pandas()["v"].to_numpy()),
        np.sort(base.to_pandas()["v"].to_numpy()),
    )


def test_tier2_disk_arenas(devices, tmp_path):
    """Forced tier 2 stages rounds through memmap arenas under the spill
    dir and still matches the oracle; the dir is cleaned up after."""
    ctx = _ctx(devices, 8)
    rng = np.random.default_rng(7)
    t = ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(0, 300, 3000).astype(np.int32),
         "v": rng.normal(size=3000).astype(np.float32)},
    )
    base = t.shuffle(["k"])
    spill_dir = tmp_path / "spill"
    spill_dir.mkdir()
    with _env(CYLON_TPU_SPILL_TIER=2, CYLON_TPU_SPILL_DIR=str(spill_dir)):
        reset_trace()
        s = t.shuffle(["k"])
        r = report("shuffle.spill.")
        assert r["shuffle.spill.tier"]["max_s"] == 2
        assert r["shuffle.spill.host_bytes"]["max_s"] > 0
    assert (s.row_counts == base.row_counts).all()
    assert np.array_equal(
        np.sort(s.to_pandas()["v"].to_numpy()),
        np.sort(base.to_pandas()["v"].to_numpy()),
    )
    # arenas freed their backing files with the shuffle
    assert list(spill_dir.iterdir()) == []


def test_fingerprint_includes_tier_decision(devices):
    """gated_fingerprint carries the spill gate state: a forced tier or a
    skew-gate flip must re-enter the plan-executable cache."""
    from cylon_tpu.plan.lazy import gated_fingerprint

    ctx = _ctx(devices, 4)
    t = ct.Table.from_pydict(
        ctx, {"k": np.arange(64, dtype=np.int32),
              "v": np.ones(64, np.float32)}
    )
    plan = t.lazy().plan
    fp0 = gated_fingerprint(plan)
    with _env(CYLON_TPU_SPILL_TIER=1):
        fp1 = gated_fingerprint(plan)
    with _env(CYLON_TPU_NO_SKEW_SPLIT=1):
        fp2 = gated_fingerprint(plan)
    assert fp0 != fp1
    assert fp0 != fp2
    assert fp1 != fp2


def test_host_arena_reserve_append_promote():
    """HostArena unit contract: exact reserve never re-copies, batches
    append contiguously, promote widens in place, and the live-bytes
    gauge sees the allocation."""
    reset_trace()
    a = _sp.HostArena(
        [("k", np.dtype(np.int32), False), ("v", np.dtype(np.float32), True)]
    )
    a.reserve(100)
    backing0 = a._bufs[0][0]  # the reserved allocation itself
    a.append_batch([
        (np.arange(60, dtype=np.int32), None),
        (np.ones(60, np.float32), np.array([True] * 59 + [False])),
    ])
    a.append_batch([
        (np.arange(40, dtype=np.int32), None),
        (np.zeros(40, np.float32), None),
    ])
    assert a.rows == 100
    (kd, kv), (vd, vv) = a.columns()
    assert kv is None
    assert np.array_equal(kd[:60], np.arange(60))
    assert vv is not None and not vv[59] and vv[60:].all()
    # exact reserve: both appends wrote into the reserved allocation
    assert a._bufs[0][0] is backing0
    assert report("shuffle.spill.")["shuffle.spill.host_bytes"]["max_s"] > 0
    a.promote(0, np.float64)
    (kd2, _), _ = a.columns()
    assert kd2.dtype == np.float64
    assert np.array_equal(kd2[:60], np.arange(60).astype(np.float64))
    before = a.rows
    a.close()
    assert before == 100 and a.rows == 0


def test_ooc_join_runs_on_unified_planner(devices):
    """The out-of-core join routes ingestion through _shuffle_many's
    spill path (staged-round counters fire) — not private spill rounds —
    and matches pandas, including dictionary-encoded string keys whose
    per-chunk dictionaries must survive the decoded arena round trip."""
    from cylon_tpu.parallel.ooc import OutOfCoreJoin

    ctx = _ctx(devices, 8)
    rng = np.random.default_rng(11)
    n = 6000
    keys = np.array([f"key{i % 700:04d}" for i in range(n)])
    rng.shuffle(keys)
    ldf = pd.DataFrame({"k": keys, "v": rng.normal(size=n).astype(np.float32)})
    rkeys = np.array([f"key{i % 900:04d}" for i in range(n // 2)])
    rdf = pd.DataFrame(
        {"k": rkeys, "w": rng.normal(size=n // 2).astype(np.float32)}
    )

    def chunks(df, m):
        for i in range(0, len(df), m):
            part = df.iloc[i : i + m]
            yield {c: part[c].to_numpy() for c in df.columns}

    reset_trace()
    job = OutOfCoreJoin(ctx, on="k", how="inner", num_buckets=8)
    sink = job.execute(chunks(ldf, 1000), chunks(rdf, 700))
    r = report("shuffle.spill.")
    assert r["shuffle.spill.shuffles"]["count"] >= 1
    assert r["shuffle.spill.staged_rounds"]["count"] >= 1
    assert r["shuffle.spill.ooc_joins"]["count"] == 1
    expect = ldf.merge(rdf, on="k", how="inner")
    assert sink.rows == len(expect)
    got = pd.DataFrame(sink.result_pydict())
    got = _sorted(
        got[["k_x", "v", "w"]].rename(columns={"k_x": "k"}),
        ["k", "v", "w"],
    )
    want = _sorted(expect, ["k", "v", "w"])[["k", "v", "w"]]
    pd.testing.assert_frame_equal(got, want, check_dtype=False, atol=1e-6)
    assert job.max_device_cap < n  # never whole-table resident


def test_tier1_bounds_staged_device_rows(devices):
    """The spilled round loop keeps at most the 2-round staging window
    device-resident: the engine's peak accounting at K=8 must land well
    under the tier-0 accounting, which stages all K rounds."""
    ctx = _ctx(devices, 8)
    rng = np.random.default_rng(13)
    t = ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(0, 800, 8192).astype(np.int32),
         "v": rng.normal(size=8192).astype(np.float32)},
    )
    # uniform keys spread ~n/world^2 rows per (src, dst) bucket; target
    # ~8 rounds over that hottest bucket
    budget = _budget_for(t, 8192 // 64, 8)

    def peak(tier):
        reset_trace()
        with _env(CYLON_TPU_SPILL_TIER=tier):
            s = t.shuffle(["k"], byte_budget=budget)
        r = report("shuffle.")
        assert r["shuffle.rounds"]["rows"] >= 4  # budget forced chunking
        return s, r["shuffle.spill.peak_device_bytes"]["max_s"]

    s0, peak0 = peak(0)
    s1, peak1 = peak(1)
    assert peak1 < peak0
    assert (s0.row_counts == s1.row_counts).all()
