"""Deterministic golden-file generator (run from repo root:
``python tests/data/gen_goldens.py``).

Reference analog: the compile-time EXECUTE toggle that regenerates golden
CSVs by writing instead of comparing (cpp/test/test_utils.hpp:31-33,111-117).
Inputs mirror the per-rank ``csv1_{RANK}.csv`` layout (cpp/test/join_test.cpp:
21-24); goldens are the GLOBAL expected result computed by pandas (the
oracle), verified in tests via the library's own Subtract — set-equality, the
reference's verification scheme (test_utils.hpp:37-59).
"""
import os

import numpy as np
import pandas as pd

HERE = os.path.dirname(os.path.abspath(__file__))
RANKS = 4
ROWS = 64  # per rank


def main():
    rng = np.random.default_rng(2026)
    alphabet = np.array(["ant", "bee", "cat", "dog", "elk", "fox"])
    sides = {}
    for side in (1, 2):
        parts = []
        for r in range(RANKS):
            df = pd.DataFrame({
                "k": rng.integers(0, 48, ROWS).astype(np.int64),
                "v": rng.integers(0, 1000, ROWS).astype(np.int64),
                "s": alphabet[rng.integers(0, len(alphabet), ROWS)],
            })
            if side == 2:
                # overlap a third of side 2's rows with side 1 rows so
                # intersect/subtract goldens are non-trivial
                src = sides[1].sample(ROWS // 3, random_state=r, replace=True)
                df.iloc[: ROWS // 3] = src.to_numpy()
            df.to_csv(os.path.join(HERE, f"csv{side}_{r}.csv"), index=False)
            parts.append(df)
        sides[side] = pd.concat(parts, ignore_index=True)

    a, b = sides[1], sides[2]
    for how in ("inner", "left", "right", "outer"):
        g = a.merge(b, on="k", how=how, suffixes=("_x", "_y"))
        g.to_csv(os.path.join(HERE, f"join_{how}.csv"), index=False)
    pd.concat([a, b]).drop_duplicates().to_csv(
        os.path.join(HERE, "union.csv"), index=False
    )
    a_rows = a.drop_duplicates()
    b_keyed = set(map(tuple, b.to_numpy().tolist()))
    a_rows[~a_rows.apply(tuple, axis=1).isin(b_keyed)].to_csv(
        os.path.join(HERE, "subtract.csv"), index=False
    )
    a_rows[a_rows.apply(tuple, axis=1).isin(b_keyed)].to_csv(
        os.path.join(HERE, "intersect.csv"), index=False
    )
    a.sort_values(["k", "v"]).to_csv(os.path.join(HERE, "sort_kv.csv"), index=False)
    a.groupby("k", as_index=False).agg(v_sum=("v", "sum")).to_csv(
        os.path.join(HERE, "groupby_sum.csv"), index=False
    )
    a.drop_duplicates().to_csv(os.path.join(HERE, "unique.csv"), index=False)
    print("goldens written to", HERE)


if __name__ == "__main__":
    main()
