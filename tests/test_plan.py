"""Planner tests: rewrite-rule firing via .explain(), lazy-vs-eager
differential parity (fixed + randomized), and the plan-fingerprint cache.

The eager ops are the oracle everywhere: the planner must never change a
result, only how it is computed.
"""
import numpy as np
import numpy.testing as npt
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu import col, lit
from cylon_tpu.plan import rules as plan_rules
from cylon_tpu.utils import tracing


def _tables(ctx, rng, n=1200, keyspace=40, val_dtype=np.float32, nulls=False):
    a = pd.DataFrame({
        "k": rng.integers(0, keyspace, n).astype(np.int32),
        "v": rng.normal(size=n).astype(val_dtype),
        "extra": rng.normal(size=n),
    })
    b = pd.DataFrame({
        "rk": rng.integers(0, keyspace, n // 2).astype(np.int32),
        "w": rng.normal(size=n // 2).astype(np.float32),
    })
    if nulls:
        a.loc[a.sample(frac=0.1, random_state=1).index, "v"] = np.nan
    return ct.Table.from_pandas(ctx, a), ct.Table.from_pandas(ctx, b)


def _sorted_pdf(t, by):
    return t.to_pandas().sort_values(by).reset_index(drop=True)


def _assert_frames_close(lp, ep, rtol=1e-4):
    assert list(lp.columns) == list(ep.columns)
    assert lp.shape == ep.shape
    for c in lp.columns:
        l, e = lp[c].to_numpy(), ep[c].to_numpy()
        if l.dtype.kind == "f" or e.dtype.kind == "f":
            npt.assert_allclose(
                l.astype(np.float64), e.astype(np.float64), rtol=rtol,
                atol=1e-5, equal_nan=True,
            )
        else:
            npt.assert_array_equal(l, e)


# ----------------------------------------------------------------------
# acceptance: filter -> join -> groupby(sum)
# ----------------------------------------------------------------------
def test_acceptance_filter_join_groupby_sum(ctx8, rng):
    ta, tb = _tables(ctx8, rng)
    lf = (
        ta.lazy()
        .join(tb.lazy(), left_on="k", right_on="rk")
        .filter(col("w") > 0.0)
        .groupby("k", {"v": "sum"})
    )
    text = lf.explain()
    # >= 3 distinct rules, including shuffle elimination and the fused
    # join+groupby pushdown selecting join_sum_by_key_pushdown
    for rule in (
        plan_rules.FILTER_PUSHDOWN,
        plan_rules.PROJECTION_PUSHDOWN,
        plan_rules.SHUFFLE_ELIM,
        plan_rules.FUSED_JOIN_GROUPBY,
    ):
        assert rule in text, f"{rule} missing from explain:\n{text}"
    assert "join_sum_by_key_pushdown" in text

    res = lf.collect()
    joined = ta.distributed_join(tb, left_on=["k"], right_on=["rk"])
    eager = joined.filter(joined.column("w").data > 0.0).groupby(
        "k", {"v": "sum"}
    )
    _assert_frames_close(_sorted_pdf(res, "k"), _sorted_pdf(eager, "k"))


def test_plan_cache_hit_no_recompile(ctx8, rng):
    ta, tb = _tables(ctx8, rng)
    lf = (
        ta.lazy()
        .join(tb.lazy(), left_on="k", right_on="rk")
        .filter(col("w") > 0.0)
        .groupby("k", {"v": "sum"})
    )
    first = lf.collect()
    hits0 = tracing.get_count("plan.cache.hit")
    kernels0 = len(ctx8._jit_cache)
    # identical plan shape + data: pure cache hit, zero new kernel programs
    second = lf.collect()
    assert tracing.get_count("plan.cache.hit") == hits0 + 1
    assert len(ctx8._jit_cache) == kernels0, "cache hit must not recompile"
    assert second.column_names == first.column_names
    # fresh LazyFrame objects over fresh (equal-schema) data: same
    # fingerprint, still a hit (sizes are jit's business, not the plan's)
    ta2, tb2 = _tables(ctx8, np.random.default_rng(7))
    lf2 = (
        ta2.lazy()
        .join(tb2.lazy(), left_on="k", right_on="rk")
        .filter(col("w") > 0.0)
        .groupby("k", {"v": "sum"})
    )
    third = lf2.collect()
    assert tracing.get_count("plan.cache.hit") == hits0 + 2
    assert third.column_names == first.column_names


# ----------------------------------------------------------------------
# individual rules
# ----------------------------------------------------------------------
def test_explain_each_rule_fires_on_trigger_plan(ctx8, rng):
    ta, tb = _tables(ctx8, rng)
    # filter pushdown: filter sits above a join whose right side covers it
    t1 = ta.lazy().join(tb.lazy(), left_on="k", right_on="rk").filter(
        col("w") > 0.0
    )
    assert plan_rules.FILTER_PUSHDOWN in t1.explain()
    # projection pushdown: select a subset after a join
    t2 = ta.lazy().join(tb.lazy(), left_on="k", right_on="rk").select(
        ["k", "w"]
    )
    assert plan_rules.PROJECTION_PUSHDOWN in t2.explain()
    # shuffle elimination: groupby on the join key of a just-shuffled join
    t3 = ta.lazy().join(tb.lazy(), left_on="k", right_on="rk").groupby(
        "k", {"w": "min"}
    )
    ex3 = t3.explain()
    assert plan_rules.SHUFFLE_ELIM in ex3
    assert plan_rules.FUSED_JOIN_GROUPBY not in ex3  # min() is not sum()
    # fused join+groupby: sum of a float32 LEFT column by the join key
    t4 = ta.lazy().join(tb.lazy(), left_on="k", right_on="rk").groupby(
        "k", {"v": "sum"}
    )
    assert plan_rules.FUSED_JOIN_GROUPBY in t4.explain()


def test_fused_rule_gates(ctx8, rng):
    ta, tb = _tables(ctx8, rng, val_dtype=np.int32)
    # int value column: generic path (wide accumulator), still correct
    lf = ta.lazy().join(tb.lazy(), left_on="k", right_on="rk").groupby(
        "k", {"v": "sum"}
    )
    assert plan_rules.FUSED_JOIN_GROUPBY not in lf.explain()
    res = lf.collect()
    joined = ta.distributed_join(tb, left_on=["k"], right_on=["rk"])
    eager = joined.groupby("k", {"v": "sum"})
    _assert_frames_close(_sorted_pdf(res, "k"), _sorted_pdf(eager, "k"))


def test_fused_path_with_null_values(ctx8, rng):
    ta, tb = _tables(ctx8, rng, nulls=True)
    lf = ta.lazy().join(tb.lazy(), left_on="k", right_on="rk").groupby(
        "k", {"v": "sum"}
    )
    assert plan_rules.FUSED_JOIN_GROUPBY in lf.explain()
    res = lf.collect()
    joined = ta.distributed_join(tb, left_on=["k"], right_on=["rk"])
    eager = joined.groupby("k", {"v": "sum"})
    _assert_frames_close(_sorted_pdf(res, "k"), _sorted_pdf(eager, "k"))


def test_shuffle_elimination_correctness(world_ctx, rng):
    """join -> groupby on the join key must equal the eager two-shuffle
    path on every mesh size (the eliminated shuffle is the one the eager
    distributed_groupby would run)."""
    ta, tb = _tables(world_ctx, rng, n=800)
    lf = ta.lazy().join(tb.lazy(), left_on="k", right_on="rk").groupby(
        "k", {"w": "max"}
    )
    res = lf.collect()
    joined = ta.distributed_join(tb, left_on=["k"], right_on=["rk"])
    eager = joined.distributed_groupby("k", {"w": "max"})
    _assert_frames_close(_sorted_pdf(res, "k"), _sorted_pdf(eager, "k"))


def test_filter_pushdown_not_through_outer_join(ctx8, rng):
    """A right-column predicate must NOT move below a LEFT join (it would
    turn matched rows into unmatched instead of dropping them)."""
    ta, tb = _tables(ctx8, rng, n=600)
    lf = (
        ta.lazy()
        .join(tb.lazy(), left_on="k", right_on="rk", how="left")
        .filter(col("w") > 0.5)
    )
    # the rule may still fire for OTHER filters; assert correctness
    res = lf.collect()
    joined = ta.distributed_join(tb, left_on=["k"], right_on=["rk"], how="left")
    from cylon_tpu.plan.expr import filter_mask

    eager = joined.filter(
        filter_mask(col("w") > 0.5, {n: joined.column(n) for n in joined.column_names})
    )
    _assert_frames_close(
        _sorted_pdf(res, ["k", "v", "w"]), _sorted_pdf(eager, ["k", "v", "w"])
    )


def test_chained_join_no_subset_elision(ctx8, rng):
    """A table partitioned on hash('a') is co-located for ('a','b') but
    PLACED differently than a fresh hash of both columns — a second join on
    ('a','b') must keep its shuffles or matches silently vanish."""
    n = 2000
    a = pd.DataFrame({"a": rng.integers(0, 20, n).astype(np.int32),
                      "b": rng.integers(0, 20, n).astype(np.int32)})
    b = pd.DataFrame({"a": rng.integers(0, 20, n).astype(np.int32),
                      "w": rng.normal(size=n).astype(np.float32)})
    c = pd.DataFrame({"a2": rng.integers(0, 20, 300).astype(np.int32),
                      "b2": rng.integers(0, 20, 300).astype(np.int32),
                      "z": rng.normal(size=300).astype(np.float32)})
    ta, tb, tc = (ct.Table.from_pandas(ctx8, x) for x in (a, b, c))
    lf = (ta.lazy().join(tb.lazy(), on="a")
          .join(tc.lazy(), left_on=["a_x", "b"], right_on=["a2", "b2"]))
    got = lf.collect().row_count
    want = len(a.merge(b, on="a").rename(columns={"a": "a_x"})
               .merge(c, left_on=["a_x", "b"], right_on=["a2", "b2"]))
    assert got == want
    # exact same-key chained join: elision IS sound and must still fire
    lf2 = (ta.lazy().join(tb.lazy(), on="a")
           .join(tc.lazy(), left_on=["a_x"], right_on=["a2"]))
    assert plan_rules.SHUFFLE_ELIM in lf2.explain()
    got2 = lf2.collect().row_count
    want2 = len(a.merge(b, on="a").rename(columns={"a": "a_x"})
                .merge(c, left_on=["a_x"], right_on=["a2"]))
    assert got2 == want2


def test_cache_isolated_from_shared_scan_mutation(ctx8, rng):
    """A cached executor must keep its compile-time scan ordinals even when
    a different plan sharing a Scan node reassigns them (ordinals are
    frozen into detached stubs at compile time)."""
    ta, tb = _tables(ctx8, rng, n=300)
    base = ta.lazy()
    p1 = base.join(tb.lazy(), left_on="k", right_on="rk")
    first = p1.collect()
    # base's Scan is shared; this plan walks it at a different DFS position
    p2 = tb.lazy().join(base, left_on="rk", right_on="k")
    p2.collect()
    again = p1.collect()  # cache hit: must still read the RIGHT tables
    assert again.row_count == first.row_count
    assert again.column_names == first.column_names


# ----------------------------------------------------------------------
# surface ops
# ----------------------------------------------------------------------
def test_lazy_local_ops(local_ctx, rng):
    df = pd.DataFrame({
        "a": rng.integers(0, 20, 300).astype(np.int64),
        "b": rng.normal(size=300),
    })
    t = ct.Table.from_pandas(local_ctx, df)
    res = (
        t.lazy().filter((col("a") >= 5) & (col("a") < 15)).select(["a", "b"])
        .sort("a").collect()
    )
    exp = df[(df.a >= 5) & (df.a < 15)].sort_values("a").reset_index(drop=True)
    got = res.to_pandas().reset_index(drop=True)
    npt.assert_array_equal(got["a"].to_numpy(), exp["a"].to_numpy())
    npt.assert_allclose(
        np.sort(got["b"].to_numpy()), np.sort(exp["b"].to_numpy())
    )


def test_lazy_sort_global(ctx8, rng):
    df = pd.DataFrame({"a": rng.permutation(1000).astype(np.int32),
                       "b": rng.normal(size=1000)})
    t = ct.Table.from_pandas(ctx8, df)
    res = t.lazy().sort("a").collect()
    eager = t.distributed_sort("a")
    npt.assert_array_equal(
        res.to_pandas()["a"].to_numpy(), eager.to_pandas()["a"].to_numpy()
    )


def test_lazy_limit_and_head(ctx8, rng):
    df = pd.DataFrame({"a": np.arange(500, dtype=np.int64)})
    t = ct.Table.from_pandas(ctx8, df)
    assert t.lazy().limit(7).collect().row_count == 7
    assert t.lazy().head().collect().row_count == 5
    assert t.lazy().limit(10_000).collect().row_count == 500


def test_lazy_union(ctx8, rng):
    a = pd.DataFrame({"a": rng.integers(0, 30, 200).astype(np.int64)})
    b = pd.DataFrame({"a": rng.integers(15, 45, 200).astype(np.int64)})
    ta, tb = ct.Table.from_pandas(ctx8, a), ct.Table.from_pandas(ctx8, b)
    res = ta.lazy().union(tb.lazy()).collect()
    eager = ta.distributed_union(tb)
    npt.assert_array_equal(
        np.sort(res.to_pandas()["a"].to_numpy()),
        np.sort(eager.to_pandas()["a"].to_numpy()),
    )


def test_lazy_string_key_join(ctx8, rng):
    a = pd.DataFrame({
        "k": rng.choice([f"s{i}" for i in range(12)], 300).astype(object),
        "v": rng.normal(size=300).astype(np.float32),
    })
    b = pd.DataFrame({
        "k": rng.choice([f"s{i}" for i in range(12)], 150).astype(object),
        "w": rng.normal(size=150).astype(np.float32),
    })
    ta, tb = ct.Table.from_pandas(ctx8, a), ct.Table.from_pandas(ctx8, b)
    lf = ta.lazy().join(tb.lazy(), on="k").groupby("k_x", {"v": "sum"})
    assert plan_rules.FUSED_JOIN_GROUPBY in lf.explain()
    res = lf.collect()
    eager = ta.distributed_join(tb, on="k").groupby("k_x", {"v": "sum"})
    _assert_frames_close(_sorted_pdf(res, "k_x"), _sorted_pdf(eager, "k_x"))


def test_lazy_string_literal_filter(ctx8, rng):
    a = pd.DataFrame({
        "k": rng.choice(["ant", "bee", "cow", "dog"], 200).astype(object),
        "v": rng.normal(size=200),
    })
    t = ct.Table.from_pandas(ctx8, a)
    res = t.lazy().filter(col("k") >= "bee").collect().to_pandas()
    exp = a[a.k >= "bee"]
    assert sorted(res["k"]) == sorted(exp["k"])
    res2 = t.lazy().filter(col("k") == "cow").collect().to_pandas()
    assert sorted(res2["k"]) == sorted(a[a.k == "cow"]["k"])


def test_lazy_dataframe_entrypoint(local_ctx, rng):
    df = ct.DataFrame({"a": [3, 1, 2], "b": [1.0, 2.0, 3.0]})
    out = df.lazy().sort("a").collect()
    npt.assert_array_equal(out.to_pandas()["a"].to_numpy(), [1, 2, 3])


def test_lazy_validates_eagerly(local_ctx):
    t = ct.Table.from_pydict(ct.CylonContext.init(), {"a": [1, 2, 3]})
    lf = t.lazy()
    with pytest.raises(KeyError):
        lf.select(["nope"])
    with pytest.raises(KeyError):
        lf.filter(col("nope") > 0)
    with pytest.raises(TypeError):
        lf.filter(lambda env: env)


def test_explain_pre_and_post_sections(ctx8, rng):
    ta, tb = _tables(ctx8, rng)
    text = (
        ta.lazy().join(tb.lazy(), left_on="k", right_on="rk")
        .groupby("k", {"v": "sum"}).explain()
    )
    assert "== Logical plan ==" in text
    assert "== Optimized plan ==" in text
    assert text.index("Logical") < text.index("Optimized")


# ----------------------------------------------------------------------
# randomized differential suite: optimized plan vs eager oracle
# ----------------------------------------------------------------------
@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_differential_random_plans(ctx8, seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(200, 1500))
    keyspace = int(rng.integers(4, 60))
    ta, tb = _tables(ctx8, rng, n=n, keyspace=keyspace,
                     nulls=bool(rng.integers(0, 2)))
    filt = bool(rng.integers(0, 2))
    agg_op = rng.choice(["sum", "min", "max", "count", "mean"])

    lf = ta.lazy().join(tb.lazy(), left_on="k", right_on="rk")
    joined = ta.distributed_join(tb, left_on=["k"], right_on=["rk"])
    if filt:
        lf = lf.filter(col("v") > 0.0)
        from cylon_tpu.plan.expr import filter_mask

        joined = joined.filter(filter_mask(
            col("v") > 0.0, {c: joined.column(c) for c in joined.column_names}
        ))
    lf = lf.groupby("k", {"v": str(agg_op)})
    eager = joined.distributed_groupby("k", {"v": str(agg_op)})
    _assert_frames_close(_sorted_pdf(lf.collect(), "k"), _sorted_pdf(eager, "k"))


# ----------------------------------------------------------------------
# observability
# ----------------------------------------------------------------------
def test_collect_emits_plan_spans_and_report(ctx8, rng):
    tracing.reset_trace()
    ta, tb = _tables(ctx8, rng, n=400)
    lf = ta.lazy().join(tb.lazy(), left_on="k", right_on="rk").groupby(
        "k", {"v": "sum"}
    )
    lf.collect()
    rep = tracing.report()
    for name in ("plan.optimize", "plan.lower", "plan.execute"):
        assert rep[name]["count"] == 1, rep
    lf.collect()
    rep = tracing.report()
    for name in ("plan.optimize", "plan.lower", "plan.execute"):
        assert rep[name]["count"] == 2, "spans must be emitted on cache hits too"
    rules_rep = tracing.report("plan.rule.")
    assert rules_rep[f"plan.rule.{plan_rules.FUSED_JOIN_GROUPBY}"]["count"] == 2
    assert rules_rep[f"plan.rule.{plan_rules.SHUFFLE_ELIM}"]["count"] == 2
    # a never-seen plan shape must register a miss in the engine stats
    misses0 = __import__("cylon_tpu").engine.plan_cache_stats()["misses"]
    ta.lazy().select(["extra", "k"]).filter(col("extra") < 0.0).collect()
    stats = __import__("cylon_tpu").engine.plan_cache_stats()
    assert stats["hits"] >= 1 and stats["misses"] == misses0 + 1
