"""Fused Pallas shuffle codec tests (ISSUE 20, ops/pallas_codec.py).

Four layers, mirroring the sort engine's test discipline
(test_radix_sort.py) and the quant tier's differential layout
(test_quant_wire.py):

  1. kernel unit differentials — fused_pack_dest (hash mode AND
     pid-input mode) against the exact XLA chain it replaces
     (hash_partition_ids -> bucket_counts -> build_send_slots_round),
     and fused_compact_move against the mask -> stable argsort ->
     gather it replaces, bit-for-bit including the dead tail;
  2. edge cases — zero-row chunks through pack_lane_buffer /
     split_header and through the fused move, garbage pids behind the
     live count, multi-round respill windows;
  3. end-to-end differentials vs the CYLON_TPU_NO_PALLAS_CODEC=1
     oracle at worlds {1, 4, 8}: bit-exact table outputs (the codec is
     lossless by contract — quantized lanes too, because both impls
     ship the SAME q8 codes and scales);
  4. gate pins — resolver ladder, structural decliners (multi-header
     quant wire, non-pow2 world), and the impl tag that keys the
     kernel caches.
"""
import os

import numpy as np
import pandas as pd
import pytest

import jax.numpy as jnp

import cylon_tpu as ct
from cylon_tpu.ops import pallas_codec as pc
from cylon_tpu.ops import partition as part
from cylon_tpu.parallel import shuffle as _sh

pytestmark = pytest.mark.skipif(
    not pc.codec_available(), reason="pallas unavailable"
)


@pytest.fixture(scope="module")
def ctx1(devices):
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:1]))


@pytest.fixture(scope="module")
def ctx4(devices):
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:4]))


@pytest.fixture(scope="module")
def ctx8(devices):
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:8]))


@pytest.fixture(autouse=True)
def _clean_env():
    saved = {
        k: os.environ.get(k)
        for k in ("CYLON_TPU_CODEC_IMPL", "CYLON_TPU_NO_PALLAS_CODEC",
                  "CYLON_TPU_QUANT_TOL")
    }
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _xla_pack(pid, world, bc, r):
    cnt = _sh.bucket_counts(pid, world)
    dest, _ = _sh.build_send_slots_round(pid, cnt, world, bc, r)
    return np.asarray(dest), np.asarray(cnt)


# ----------------------------------------------------------------------
# 1. kernel unit differentials
# ----------------------------------------------------------------------

@pytest.mark.parametrize("world", [2, 4, 8])
def test_fused_pack_hash_mode_matches_xla_chain(world, rng):
    cap, n = 1024, 900
    kcols = [
        (jnp.asarray(rng.integers(-5000, 5000, cap).astype(np.int32)),
         jnp.asarray(rng.integers(0, 2, cap).astype(bool))),
        (jnp.asarray((rng.normal(size=cap) * 40).astype(np.float32)), None),
    ]
    pid = part.hash_partition_ids(kcols, jnp.int32(n), world)
    words, valids, hv = pc.hash_operands(kcols)
    # bc small enough that hot buckets respill: rounds 0 and 1 both
    # carry rows and round 2 is all-dropped — every window is exercised
    bc = (n // world) // 2
    for r in range(3):
        dest, cnt = _xla_pack(pid, world, bc, r)
        dest_f, cnt_f = pc.fused_pack_dest(
            words, valids, hv, jnp.int32(n), r, world, bc, interpret=True
        )
        assert np.array_equal(np.asarray(cnt_f), cnt)
        assert np.array_equal(np.asarray(dest_f), dest), f"round {r}"


@pytest.mark.parametrize("world", [4, 8])
def test_fused_pack_pid_mode_matches_xla_chain(world, rng):
    """pid-input mode (range/task/semi packs): the kernel consumes an
    XLA pid lane carrying the shared pid == P dead sentinel — for
    filtered live rows AND for garbage behind the live count, which the
    kernel's own rowid < n fold must drop."""
    cap, n = 1024, 800
    pid_np = rng.integers(0, world + 1, cap).astype(np.int32)  # incl. P
    garbage = pid_np.copy()
    garbage[n:] = rng.integers(0, world, cap - n)  # junk past n
    ref_pid = pid_np.copy()
    ref_pid[n:] = world  # the sentinel compute_pid guarantees
    bc = (n // world) // 2
    for r in range(2):
        dest, cnt = _xla_pack(jnp.asarray(ref_pid), world, bc, r)
        dest_f, cnt_f = pc.fused_pack_dest(
            [], [], (), jnp.int32(n), r, world, bc,
            pid=jnp.asarray(garbage), interpret=True,
        )
        assert np.array_equal(np.asarray(cnt_f), cnt)
        assert np.array_equal(np.asarray(dest_f), dest), f"round {r}"


def test_fused_compact_matches_argsort_gather(rng):
    world, bc, lm = 8, 16, 3
    move = jnp.asarray(
        rng.integers(-(2 ** 31), 2 ** 31 - 1, (world * bc, lm)).astype(
            np.int32
        )
    )
    for counts in (
        rng.integers(0, bc + 1, world).astype(np.int32),
        np.zeros(world, np.int32),                      # nothing received
        np.full(world, bc, np.int32),                   # every slot live
        np.array([bc, 0, 3, 0, bc, 1, 0, 7], np.int32),  # zero-row chunks
    ):
        rc = jnp.asarray(counts)
        mask, total = _sh.received_row_mask(rc, world, bc)
        order = jnp.argsort(~mask, stable=True)
        ref = np.asarray(move[order])
        moved, tot = pc.fused_compact_move(move, rc, world, bc,
                                           interpret=True)
        assert int(tot) == int(total) == int(counts.sum())
        assert np.array_equal(np.asarray(moved), ref), counts


# ----------------------------------------------------------------------
# 2. edge cases through the shared XLA scatter/header helpers
# ----------------------------------------------------------------------

def test_zero_row_chunks_through_pack_and_split(rng):
    """Buckets with zero rows: the fused dest/cnt drive the SAME
    pack_lane_buffer scatter and split_header strip as the XLA chain —
    empty chunks keep a zero header count and all-dead data rows."""
    world, cap, n, bc = 8, 512, 400, 64
    # rows only for even-numbered buckets; odd buckets are empty
    pid_np = (rng.integers(0, world // 2, cap) * 2).astype(np.int32)
    pid_np[n:] = world
    pid = jnp.asarray(pid_np)
    dest_f, cnt_f = pc.fused_pack_dest(
        [], [], (), jnp.int32(n), 0, world, bc, pid=pid, interpret=True
    )
    dest_x, cnt_x = _xla_pack(pid, world, bc, 0)
    assert np.array_equal(np.asarray(cnt_f), cnt_x)
    lanes = [jnp.asarray(rng.integers(0, 1000, cap).astype(np.int32))]
    rcnt = _sh.round_counts(cnt_f, bc, 0)
    buf_f = _sh.pack_lane_buffer(lanes, dest_f, rcnt, world, bc)
    buf_x = _sh.pack_lane_buffer(lanes, jnp.asarray(dest_x), rcnt, world, bc)
    assert np.array_equal(np.asarray(buf_f), np.asarray(buf_x))
    data, recv = _sh.split_header(buf_f, world)
    assert np.array_equal(np.asarray(recv), np.asarray(rcnt))
    assert np.asarray(recv)[1::2].sum() == 0  # odd chunks: zero rows
    # and the fused move handles those zero-row chunks exactly
    mask, _tot = _sh.received_row_mask(recv, world, bc)
    order = jnp.argsort(~mask, stable=True)
    moved, tot = pc.fused_compact_move(data, recv, world, bc,
                                       interpret=True)
    assert np.array_equal(np.asarray(moved), np.asarray(data[order]))
    assert int(tot) == int(np.asarray(rcnt).sum())


def test_pack_single_partition_world():
    """world=1 (pow2): everything lands in bucket 0; sentinel rows drop."""
    cap, n, bc = 256, 200, 256
    pid = jnp.asarray(
        np.r_[np.zeros(n, np.int32), np.ones(cap - n, np.int32)]
    )
    dest_f, cnt_f = pc.fused_pack_dest(
        [], [], (), jnp.int32(n), 0, 1, bc, pid=pid, interpret=True
    )
    dest_x, cnt_x = _xla_pack(pid, 1, bc, 0)
    assert np.array_equal(np.asarray(cnt_f), cnt_x)
    assert np.array_equal(np.asarray(dest_f), dest_x)


# ----------------------------------------------------------------------
# 3. end-to-end differentials vs the kill-switch oracle
# ----------------------------------------------------------------------

def _diff_tables(out, ref):
    cols = list(out.columns)
    o = out.sort_values(cols).reset_index(drop=True)
    r = ref.sort_values(cols).reset_index(drop=True)
    pd.testing.assert_frame_equal(o, r)


def _join_frames(rng, n=700):
    la = pd.DataFrame({
        "k": rng.integers(0, 150, n).astype(np.int64),
        "v": rng.normal(size=n),                      # f64 lane
        "s": rng.normal(size=n).astype(np.float32),
    })
    lb = pd.DataFrame({
        "k": rng.integers(0, 150, n).astype(np.int64),
        "w": (rng.normal(size=n) * 10).astype(np.float32),
    })
    return la, lb


@pytest.mark.parametrize("ctxname", ["ctx1", "ctx4", "ctx8"])
def test_join_bit_exact_vs_oracle(ctxname, request, rng):
    ctx = request.getfixturevalue(ctxname)
    la, lb = _join_frames(rng)
    ta = ct.Table.from_pandas(ctx, la)
    tb = ct.Table.from_pandas(ctx, lb)
    os.environ["CYLON_TPU_CODEC_IMPL"] = "pallas"
    out = ta.distributed_join(tb, on=["k"]).to_pandas()
    with pc.disabled():
        ref = ta.distributed_join(tb, on=["k"]).to_pandas()
    assert len(out) > 0
    _diff_tables(out, ref)


@pytest.mark.parametrize("ctxname", ["ctx4", "ctx8"])
def test_groupby_bit_exact_vs_oracle(ctxname, request, rng):
    """Non-semi hash shuffle: the pack kernel's hash-fused mode."""
    ctx = request.getfixturevalue(ctxname)
    df = pd.DataFrame({
        "g": rng.integers(0, 60, 900).astype(np.int64),
        "x": rng.normal(size=900),
    })
    t = ct.Table.from_pandas(ctx, df)
    os.environ["CYLON_TPU_CODEC_IMPL"] = "pallas"
    out = t.distributed_groupby(["g"], {"x": "sum"}).to_pandas()
    with pc.disabled():
        ref = t.distributed_groupby(["g"], {"x": "sum"}).to_pandas()
    _diff_tables(out, ref)


def test_quantized_wire_bit_exact_vs_oracle(ctx4, rng):
    """All-quantized packs (pack_cols_quant): the multi-header q8 wire
    declines the pack kernel but keeps the fused compact — and both
    codec impls ship identical q8 codes + scales, so even the lossy
    lanes diff EXACTLY between impls."""
    df_a = pd.DataFrame({
        "k": rng.integers(0, 100, 600).astype(np.int32),
        "a": (rng.normal(size=600) * 30).astype(np.float32),
        "b": (rng.normal(size=600) * 5).astype(np.float32),
    })
    df_b = pd.DataFrame({
        "k": rng.integers(0, 100, 500).astype(np.int32),
        "c": (rng.normal(size=500) * 2).astype(np.float32),
    })
    ta = ct.Table.from_pandas(ctx4, df_a)
    tb = ct.Table.from_pandas(ctx4, df_b)
    os.environ["CYLON_TPU_QUANT_TOL"] = "1e-2"
    os.environ["CYLON_TPU_CODEC_IMPL"] = "pallas"
    out = ta.distributed_join(tb, on=["k"]).to_pandas()
    with pc.disabled():
        ref = ta.distributed_join(tb, on=["k"]).to_pandas()
    _diff_tables(out, ref)


def test_f64_passthrough_lane_vs_oracle(ctx4, rng):
    """f64 payload columns ride the passthrough gather keyed by the
    fused move's carried order lane — bit-exact against the oracle's
    argsort-gather order."""
    df = pd.DataFrame({
        "k": rng.integers(0, 80, 640).astype(np.int64),
        "p": rng.normal(size=640),  # float64 passthrough
    })
    t = ct.Table.from_pandas(ctx4, df)
    os.environ["CYLON_TPU_CODEC_IMPL"] = "pallas"
    out = t.distributed_sort(["k"]).to_pandas()
    with pc.disabled():
        ref = t.distributed_sort(["k"]).to_pandas()
    pd.testing.assert_frame_equal(
        out.reset_index(drop=True), ref.reset_index(drop=True)
    )


# ----------------------------------------------------------------------
# 4. gate pins
# ----------------------------------------------------------------------

def test_resolver_ladder_and_tag():
    os.environ.pop("CYLON_TPU_CODEC_IMPL", None)
    os.environ.pop("CYLON_TPU_NO_PALLAS_CODEC", None)
    assert pc.resolved_impl() == "pallas"
    os.environ["CYLON_TPU_CODEC_IMPL"] = "xla"
    assert pc.resolved_impl() == "xla"
    tag_x = pc.impl_tag()
    os.environ["CYLON_TPU_CODEC_IMPL"] = "pallas"
    tag_p = pc.impl_tag()
    assert tag_x != tag_p and tag_x[0] == "codec_impl"
    os.environ.pop("CYLON_TPU_CODEC_IMPL", None)
    with pc.disabled():
        assert pc.resolved_impl() == "xla"
        assert not pc.gate_state()[0]
    assert pc.gate_state()[0]


def test_structural_decliners():
    # multi-header quant wire declines the pack kernel
    assert pc.pack_supported("hash", False, True, 1, 8)
    assert not pc.pack_supported("hash", False, True, 2, 8)
    # non-pow2 / oversized worlds decline
    assert not pc.pack_supported("hash", False, True, 1, 6)
    assert not pc.pack_supported("hash", False, True, 1, 2048)
    # kind/semi select the MODE, not engagement
    assert pc.pack_supported("range", False, True, 1, 8)
    assert pc.pack_supported("hash", True, True, 1, 8)
    assert pc.pack_fuses_hash("hash", False)
    assert not pc.pack_fuses_hash("hash", True)
    assert not pc.pack_fuses_hash("range", False)
    # compact: topo branch and VMEM-overflow move matrices decline
    assert pc.compact_supported(True, False, 8, 64, 4)
    assert not pc.compact_supported(True, True, 8, 64, 4)
    assert not pc.compact_supported(False, False, 8, 64, 4)
    big = pc.COMPACT_VMEM_BUDGET
    assert not pc.compact_supported(True, False, 8, big, 4)


def test_row_pass_tables_agree_with_census():
    from cylon_tpu.analysis import contracts as _c
    from cylon_tpu.obs import prof as _p

    assert pc.PACK_ROW_PASSES == _c.CODEC_PACK_ROW_PASSES
    assert pc.COMPACT_ROW_PASSES == _c.CODEC_COMPACT_ROW_PASSES
    for impl, passes in pc.PACK_ROW_PASSES.items():
        assert _p.PACK_WEIGHT_BY_IMPL[impl] == float(passes)
    for impl, passes in pc.COMPACT_ROW_PASSES.items():
        assert _p.COMPACT_WEIGHT_BY_IMPL[impl] == float(passes)
    assert pc.pack_row_passes("pallas", fuse_hash=False) == 2
    assert pc.pack_row_passes("pallas") == 1
    assert pc.pack_row_passes("xla", fuse_hash=False) == 3
