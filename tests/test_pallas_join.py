"""Pallas PK-FK join probe (ops/pallas_join.py) vs a pandas oracle.

Runs in pallas interpret mode on the CPU mesh; the same kernel compiles to
Mosaic on a real TPU (benchmarks/pallas_bench.py measures it head-to-head
against the sort-based spec_join).
"""
import numpy as np
import pandas as pd
import pytest

import jax.numpy as jnp

from cylon_tpu.ops.pallas_join import pk_inner_join


def _run(lk, rk, B=64, nb=0):
    cap_l, cap_r = len(lk), len(rk)
    l_idx, r_idx, total, bad = pk_inner_join(
        jnp.asarray(lk), jnp.asarray(rk),
        jnp.int32(cap_l), jnp.int32(cap_r),
        nb=nb, B=B, interpret=True,
    )
    return (
        np.asarray(l_idx), np.asarray(r_idx), int(total), int(bad),
    )


def test_pk_join_matches_pandas():
    rng = np.random.default_rng(0)
    n = 512
    rk = rng.permutation(1024)[:n].astype(np.int32)  # unique PK
    lk = rng.choice(rk, size=n, replace=True).astype(np.int32)  # FK hits
    lk[::7] = 5000 + np.arange(len(lk[::7]))  # some misses
    l_idx, r_idx, total, bad = _run(lk, rk)
    assert bad == 0

    expect = pd.DataFrame({"k": lk, "li": np.arange(n)}).merge(
        pd.DataFrame({"k": rk, "ri": np.arange(n)}), on="k"
    )
    assert total == len(expect)
    got = set(zip(l_idx[:total].tolist(), r_idx[:total].tolist()))
    want = set(zip(expect["li"].tolist(), expect["ri"].tolist()))
    assert got == want


def test_pk_join_reports_duplicate_right():
    lk = np.arange(32, dtype=np.int32)
    rk = np.array([1, 2, 2, 3] + list(range(10, 38)), dtype=np.int32)
    _, _, _, bad = _run(lk, rk)
    assert bad != 0  # caller must fall back to the exact join


def test_pk_join_reports_bucket_overflow():
    # nb=2 buckets of B=4: 32 keys cannot fit -> overflow flag
    lk = np.arange(32, dtype=np.int32)
    rk = np.arange(32, dtype=np.int32)
    _, _, _, bad = _run(lk, rk, B=4, nb=2)
    assert bad != 0


def test_pk_join_partial_live_counts():
    lk = np.array([5, 6, 7, 99, 99, 99], dtype=np.int32)
    rk = np.array([7, 5, 42, 99, 99, 99], dtype=np.int32)
    cap = len(lk)
    l_idx, r_idx, total, bad = (
        np.asarray(x) if not np.isscalar(x) else x
        for x in pk_inner_join(
            jnp.asarray(lk), jnp.asarray(rk),
            jnp.int32(3), jnp.int32(3),  # only first 3 rows live
            B=8, interpret=True,
        )
    )
    assert int(bad) == 0
    assert int(total) == 2  # 5 and 7 match; padding 99s must not
    pairs = set(zip(np.asarray(l_idx)[:2].tolist(), np.asarray(r_idx)[:2].tolist()))
    assert pairs == {(0, 1), (2, 0)}
