"""Pallas PK-FK join probe (ops/pallas_join.py) vs a pandas oracle.

Runs in pallas interpret mode on the CPU mesh; the same kernel compiles to
Mosaic on a real TPU (benchmarks/pallas_bench.py measures it head-to-head
against the sort-based spec_join).
"""
import numpy as np
import pandas as pd
import pytest

import jax.numpy as jnp

from cylon_tpu.ops.pallas_join import pk_inner_join


def _run(lk, rk, B=64, nb=0):
    cap_l, cap_r = len(lk), len(rk)
    l_idx, r_idx, total, bad = pk_inner_join(
        jnp.asarray(lk), jnp.asarray(rk),
        jnp.int32(cap_l), jnp.int32(cap_r),
        nb=nb, B=B, interpret=True,
    )
    return (
        np.asarray(l_idx), np.asarray(r_idx), int(total), int(bad),
    )


def test_pk_join_matches_pandas():
    rng = np.random.default_rng(0)
    n = 512
    rk = rng.permutation(1024)[:n].astype(np.int32)  # unique PK
    lk = rng.choice(rk, size=n, replace=True).astype(np.int32)  # FK hits
    lk[::7] = 5000 + np.arange(len(lk[::7]))  # some misses
    l_idx, r_idx, total, bad = _run(lk, rk)
    assert bad == 0

    expect = pd.DataFrame({"k": lk, "li": np.arange(n)}).merge(
        pd.DataFrame({"k": rk, "ri": np.arange(n)}), on="k"
    )
    assert total == len(expect)
    got = set(zip(l_idx[:total].tolist(), r_idx[:total].tolist()))
    want = set(zip(expect["li"].tolist(), expect["ri"].tolist()))
    assert got == want


def test_pk_join_reports_duplicate_right():
    lk = np.arange(32, dtype=np.int32)
    rk = np.array([1, 2, 2, 3] + list(range(10, 38)), dtype=np.int32)
    _, _, _, bad = _run(lk, rk)
    assert bad != 0  # caller must fall back to the exact join


def test_pk_join_reports_bucket_overflow():
    # nb=2 buckets of B=4: 32 keys cannot fit -> overflow flag
    lk = np.arange(32, dtype=np.int32)
    rk = np.arange(32, dtype=np.int32)
    _, _, _, bad = _run(lk, rk, B=4, nb=2)
    assert bad != 0


# ------------------------------------------------------- algorithm surface
def test_join_algorithm_pallas_pk(world_ctx, rng):
    """Table.join(algorithm='pallas_pk') — the JoinConfig SORT/HASH-style
    algorithm selector with the Pallas probe; values checked per shard."""
    import cylon_tpu as ct

    n = 240
    rkeys = rng.permutation(5000)[:n].astype(np.int32)
    lkeys = rng.choice(rkeys, n).astype(np.int32)
    lkeys[::6] = 90000 + np.arange(len(lkeys[::6]))  # misses
    lt = ct.Table.from_pydict(
        world_ctx, {"k": lkeys, "v": rng.normal(size=n).astype(np.float32)}
    )
    rt = ct.Table.from_pydict(
        world_ctx, {"k": rkeys, "w": rng.normal(size=n).astype(np.float32)}
    )
    got = lt.join(rt, on="k", algorithm="pallas_pk")
    want = lt.join(rt, on="k")  # the exact sort-based local join
    assert got.row_count == want.row_count
    g = got.to_pandas().sort_values(["k_x", "v"]).reset_index(drop=True)
    w = want.to_pandas().sort_values(["k_x", "v"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(g, w, check_dtype=False, atol=1e-6)


def test_join_algorithm_pallas_pk_falls_back_on_duplicates(ctx8, rng):
    import cylon_tpu as ct

    lkeys = rng.integers(0, 40, 160).astype(np.int32)
    rkeys = rng.integers(0, 40, 120).astype(np.int32)  # heavy duplicates
    lt = ct.Table.from_pydict(ctx8, {"k": lkeys})
    rt = ct.Table.from_pydict(ctx8, {"k": rkeys})
    got = lt.join(rt, on="k", algorithm="pallas_pk")
    want = lt.join(rt, on="k")
    assert got.row_count == want.row_count  # exact fallback, no wrong answer


def test_join_algorithm_pallas_pk_rejects_unsupported(ctx8, rng):
    import cylon_tpu as ct

    lt = ct.Table.from_pydict(ctx8, {"k": rng.normal(size=16).astype(np.float32)})
    rt = ct.Table.from_pydict(ctx8, {"k": rng.normal(size=16).astype(np.float32)})
    with pytest.raises(ValueError, match="pallas_pk"):
        lt.join(rt, on="k", algorithm="pallas_pk")
    lt2 = ct.Table.from_pydict(ctx8, {"k": np.arange(8, dtype=np.int32)})
    with pytest.raises(ValueError, match="inner"):
        lt2.join(lt2, on="k", how="left", algorithm="pallas_pk")


def test_distributed_join_pallas_pk(world_ctx, rng):
    """algorithm= flows through the distributed path: shuffle co-partitions
    the keys, then the per-shard Pallas probe answers globally."""
    import cylon_tpu as ct

    n = 300
    rkeys = rng.permutation(3000)[:n].astype(np.int32)
    lkeys = rng.choice(rkeys, n).astype(np.int32)
    lt = ct.Table.from_pydict(
        world_ctx, {"k": lkeys, "v": rng.normal(size=n).astype(np.float32)}
    )
    rt = ct.Table.from_pydict(
        world_ctx, {"k": rkeys, "w": rng.normal(size=n).astype(np.float32)}
    )
    got = lt.distributed_join(rt, on="k", how="inner", algorithm="pallas_pk")
    want = lt.distributed_join(rt, on="k", how="inner")
    assert got.row_count == want.row_count
    g = got.to_pandas().sort_values(["k_x", "v"]).reset_index(drop=True)
    w = want.to_pandas().sort_values(["k_x", "v"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(g, w, check_dtype=False, atol=1e-6)


def test_join_config_pallas_pk_algorithm(ctx8, rng):
    import cylon_tpu as ct
    from cylon_tpu.join_config import JoinConfig

    rkeys = rng.permutation(500)[:60].astype(np.int32)
    lt = ct.Table.from_pydict(ctx8, {"k": rng.choice(rkeys, 60).astype(np.int32)})
    rt = ct.Table.from_pydict(ctx8, {"k": rkeys})
    cfg = JoinConfig.inner_join(on="k", algorithm="pallas_pk")
    got = lt.join(rt, config=cfg)
    want = lt.join(rt, on="k")
    assert got.row_count == want.row_count


def test_pk_join_partial_live_counts():
    lk = np.array([5, 6, 7, 99, 99, 99], dtype=np.int32)
    rk = np.array([7, 5, 42, 99, 99, 99], dtype=np.int32)
    cap = len(lk)
    l_idx, r_idx, total, bad = (
        np.asarray(x) if not np.isscalar(x) else x
        for x in pk_inner_join(
            jnp.asarray(lk), jnp.asarray(rk),
            jnp.int32(3), jnp.int32(3),  # only first 3 rows live
            B=8, interpret=True,
        )
    )
    assert int(bad) == 0
    assert int(total) == 2  # 5 and 7 match; padding 99s must not
    pairs = set(zip(np.asarray(l_idx)[:2].tolist(), np.asarray(r_idx)[:2].tolist()))
    assert pairs == {(0, 1), (2, 0)}


def test_pk_join_nb_not_multiple_of_block_group(rng):
    """Public nb values that are NOT multiples of the per-program bucket
    group G must still probe every bucket (the grid is nb // G with G a
    DIVISOR of nb; a truncating nb // 8 once silently skipped the trailing
    buckets and emitted wrong rows with bad=0)."""
    n = 5000
    lk = jnp.asarray(rng.permutation(4 * n)[:n].astype(np.int32))
    rk = jnp.asarray(np.arange(2 * n, dtype=np.int32))
    lkn, rkn = np.asarray(lk), np.asarray(rk)
    exp = int(np.isin(lkn, rkn).sum())
    checked = []
    for nb in (12, 6, 3, 16, 8, 2):
        li, ri, tot, bad = pk_inner_join(
            lk, rk, jnp.int32(n), jnp.int32(2 * n),
            nb=nb, B=8192, interpret=True,
        )
        if int(bad):
            continue  # overflow correctly flagged -> caller falls back
        lv, rv = np.asarray(li), np.asarray(ri)
        m = lv >= 0
        assert int(tot) == exp == m.sum(), (nb, int(tot), exp)
        assert (lkn[lv[m]] == rkn[rv[m]]).all(), nb
        checked.append(nb)
    assert len(checked) >= 4, checked
