"""Regression tests for round-2 advisor findings (ADVICE.md):

1. object-column ints outside int64 range must not crash encode_host
   (column.py int inference path);
2. _global_rowid_column must refuse >2^31-1 global rows instead of wrapping
   (table.py global rowid lane);
3. the fused-join retry loop must diagnose the int32 wrap sentinel cleanly
   instead of recompiling with an overflowing capacity (table.py retry loop,
   sentinel from parallel/pipeline.py:113-115).
"""
import numpy as np
import pytest
from unittest import mock

import cylon_tpu as ct
from cylon_tpu.column import Column
from cylon_tpu.dtypes import Type


def test_object_int_beyond_int64_falls_back_to_dictionary():
    vals = np.array([2**70, 3, None], dtype=object)
    data, valid, dtype, dictionary = Column.encode_host(vals)
    assert dtype.type == Type.STRING
    assert dictionary is not None
    decoded = dictionary[data]
    assert str(2**70) in set(decoded.tolist())
    assert valid is not None and valid.tolist() == [True, True, False]


def test_object_int_within_int64_still_exact():
    vals = np.array([2**62, -5, None], dtype=object)
    data, valid, dtype, dictionary = Column.encode_host(vals)
    assert dictionary is None
    assert data.dtype == np.int64
    assert data[0] == 2**62


def test_object_mixed_int_float_still_float64():
    vals = np.array([1, 2.5, None], dtype=object)
    data, valid, dtype, dictionary = Column.encode_host(vals)
    assert data.dtype == np.float64
    assert data[1] == 2.5


def test_global_rowid_refuses_int32_overflow(ctx8):
    tbl = ct.Table.from_pydict(ctx8, {"a": np.arange(16, dtype=np.int32)})
    tbl._shard_cap = (2**31 - 1) // ctx8.world_size + 1
    with pytest.raises(ValueError, match="int32 range"):
        tbl._global_rowid_column()


def test_fused_join_wrap_sentinel_raises_cleanly(ctx8):
    n = 64
    rng = np.random.default_rng(0)
    tbl = ct.Table.from_pydict(
        ctx8, {"k": rng.integers(0, 8, n).astype(np.int32)}
    )
    other = ct.Table.from_pydict(
        ctx8, {"k": rng.integers(0, 8, n).astype(np.int32)}
    )
    P = ctx8.world_size

    # fused join's single host sync fetches concat(nout[P], overflow[P,2]);
    # forge the saturated join-lane sentinel the pipeline emits on int32 wrap
    forged = np.concatenate(
        [np.zeros(P, np.int64), np.tile([0, 2**31 - 1], P)]
    )
    with mock.patch("cylon_tpu.table._fetch", return_value=forged):
        with pytest.raises(RuntimeError, match="mode='eager'"):
            tbl.distributed_join(other, on="k", how="inner", mode="fused")
