"""Streaming op-DAG engine (parallel/dag.py) vs eager ops as the oracle.

Reference analog: the ops/ graph examples (DisJoinOP/DisUnionOp) validated
against the eager table API, like cpp's union/join example binaries.
"""
import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.parallel import dag


def _chunks(ctx, df, n_chunks):
    size = (len(df) + n_chunks - 1) // n_chunks
    return [
        ct.Table.from_pandas(ctx, df.iloc[i * size:(i + 1) * size].reset_index(drop=True))
        for i in range(n_chunks)
        if len(df.iloc[i * size:(i + 1) * size])
    ]


@pytest.fixture
def join_data(rng):
    l = pd.DataFrame({"k": rng.integers(0, 40, 200), "x": rng.normal(size=200)})
    r = pd.DataFrame({"k": rng.integers(0, 40, 150), "y": rng.normal(size=150)})
    return l, r


def test_dis_join_streaming(ctx8, join_data):
    l, r = join_data
    g = dag.DisJoinOp(on="k", how="inner")
    out = g.execute(_chunks(ctx8, l, 3), _chunks(ctx8, r, 2))
    exp = l.merge(r, on="k", how="inner")
    assert out.row_count == len(exp)
    got = np.sort(out.to_pandas()["x"].to_numpy())
    assert np.allclose(got, np.sort(exp["x"].to_numpy()))


def test_dis_join_all_types(ctx8, join_data):
    l, r = join_data
    for how in ("left", "right", "outer"):
        g = dag.DisJoinOp(on="k", how=how)
        out = g.execute(_chunks(ctx8, l, 2), _chunks(ctx8, r, 2))
        assert out.row_count == len(l.merge(r, on="k", how=how)), how


def test_dis_union_streaming(ctx8, rng):
    a = pd.DataFrame({"k": rng.integers(0, 20, 80), "v": rng.integers(0, 3, 80)})
    b = pd.DataFrame({"k": rng.integers(0, 20, 60), "v": rng.integers(0, 3, 60)})
    g = dag.DisUnionOp(columns=["k", "v"])
    out = g.execute(_chunks(ctx8, a, 2), _chunks(ctx8, b, 3))
    exp = pd.concat([a, b]).drop_duplicates()
    assert out.row_count == len(exp)


def test_execution_strategies(local_ctx, join_data):
    """All four schedulers produce the same result on the same graph shape."""
    l, r = join_data
    exp = len(l.merge(r, on="k", how="inner"))

    def build():
        lp = dag.PartitionOp("pl")
        rp = dag.PartitionOp("pr")
        join = dag.JoinOp("join", on="k", how="inner")
        root = dag.RootOp()
        lp.add_child(join, edge=0)
        rp.add_child(join, edge=1)
        join.add_child(root)
        return lp, rp, join, root

    for make_exec in (
        lambda lp, rp, join, root: dag.SequentialExecution(lp, rp),
        lambda lp, rp, join, root: dag.RoundRobinExecution(lp, rp),
        lambda lp, rp, join, root: dag.PriorityExecution(lp, rp, priorities={"pl": 2}),
        lambda lp, rp, join, root: dag.JoinExecution(lp, rp, join, root),
    ):
        lp, rp, join, root = build()
        g = dag._StreamingGraph([lp, rp], root, make_exec(lp, rp, join, root))
        out = g.execute(_chunks(local_ctx, l, 3), _chunks(local_ctx, r, 2))
        assert out.row_count == exp, type(g.execution).__name__


def test_map_and_merge_ops(local_ctx, rng):
    df = pd.DataFrame({"v": rng.normal(size=100)})
    src = dag.MapOp("double", lambda t: ct.compute.math_op(t, "mul", 2.0))
    merge = dag.MergeOp()
    root = dag.RootOp()
    src.add_child(merge)
    merge.add_child(root)
    g = dag._StreamingGraph([src], root, dag.SequentialExecution(src))
    out = g.execute(_chunks(local_ctx, df, 4))
    assert out.row_count == 100
    assert np.allclose(
        np.sort(out.to_pandas()["v"]), np.sort(df["v"].to_numpy() * 2)
    )


def test_stall_detection(local_ctx):
    """A graph whose source is never FIN'd must raise, not spin."""
    src = dag.MapOp("id", lambda t: t)
    root = dag.RootOp()
    src.add_child(root)
    ex = dag.RoundRobinExecution(src)
    src.insert(ct.Table.from_pydict(local_ctx, {"v": np.arange(4)}))
    # drain the chunk but never call src.finish()
    with pytest.raises(RuntimeError, match="stalled"):
        ex.run()


def test_insert_after_fin_raises(local_ctx):
    src = dag.MapOp("id", lambda t: t)
    src.finish()
    with pytest.raises(RuntimeError, match="after FIN"):
        src.insert(ct.Table.from_pydict(local_ctx, {"v": np.arange(2)}))


def test_join_left_on_right_on_distributed(ctx8, rng):
    """DisJoinOp must shuffle each side on ITS key (not column 0) so
    differently-named keys stay co-partitioned (dag.py DisJoinOp)."""
    l = pd.DataFrame({"x": rng.normal(size=120), "ka": rng.integers(0, 30, 120)})
    r = pd.DataFrame({"kb": rng.integers(0, 30, 90), "y": rng.normal(size=90)})
    g = dag.DisJoinOp(left_on=["ka"], right_on=["kb"], how="inner")
    out = g.execute(_chunks(ctx8, l, 2), _chunks(ctx8, r, 2))
    assert out.row_count == len(l.merge(r, left_on="ka", right_on="kb"))


def test_empty_stream_rejected(ctx8, join_data):
    l, r = join_data
    g = dag.DisJoinOp(on="k")
    with pytest.raises(ValueError, match="at least one"):
        g.execute(_chunks(ctx8, l, 2), [])


def test_zero_row_chunk_ok(ctx8, join_data):
    """Zero-row chunks carry schema and join fine."""
    l, r = join_data
    empty = ct.Table.from_pandas(ctx8, r.iloc[:0])
    g = dag.DisJoinOp(on="k", how="left")
    out = g.execute(_chunks(ctx8, l, 2), [empty])
    assert out.row_count == len(l)


def test_string_keys_chunked_distributed(ctx8, rng):
    """Chunk-local dictionaries must not break shuffle routing: the hash
    partitioner hashes string VALUES (ops/hash.py hash_dictionary_host), so
    equal keys from different chunks co-partition."""
    words = np.array([f"key{i:03d}" for i in range(30)])
    l = pd.DataFrame({"k": words[rng.integers(0, 30, 140)], "x": rng.normal(size=140)})
    r = pd.DataFrame({"k": words[rng.integers(0, 30, 100)], "y": rng.normal(size=100)})
    g = dag.DisJoinOp(on="k", how="inner")
    out = g.execute(_chunks(ctx8, l, 3), _chunks(ctx8, r, 2))
    assert out.row_count == len(l.merge(r, on="k"))


def test_mixed_width_int_keys_chunked_distributed(ctx8, rng):
    """int32-vs-int64 keys co-partition without explicit promotion: hashing
    is width-independent (ops/hash.py _to_words two-word scheme)."""
    l = pd.DataFrame({"k": rng.integers(0, 30, 120).astype(np.int32),
                      "x": rng.normal(size=120)})
    r = pd.DataFrame({"k": rng.integers(0, 30, 90).astype(np.int64),
                      "y": rng.normal(size=90)})
    g = dag.DisJoinOp(on="k", how="inner")
    out = g.execute(_chunks(ctx8, l, 2), _chunks(ctx8, r, 2))
    assert out.row_count == len(l.merge(r, on="k"))


def test_string_union_chunked_distributed(ctx8, rng):
    words = np.array(["ant", "bee", "cat", "dog"])
    a = pd.DataFrame({"s": words[rng.integers(0, 4, 60)]})
    b = pd.DataFrame({"s": words[rng.integers(0, 4, 50)]})
    g = dag.DisUnionOp(columns=["s"])
    out = g.execute(_chunks(ctx8, a, 2), _chunks(ctx8, b, 2))
    assert out.row_count == len(pd.concat([a, b]).drop_duplicates())
