"""Topology-aware hierarchical shuffle pins (ISSUE 17).

The 2-D ``(outer x inner)`` mesh factorization decomposes the fused
exchange into a two-hop shuffle: an inner-axis grouped all_to_all that
combines rows bound for the same remote outer group, then an outer-axis
grouped all_to_all shipping the combined buffers. These tests pin:

- host planning: mesh parsing, group tables, the exact cross-outer
  capacity (``plan_two_hop``) and the per-axis byte ledger formulas;
- exact differentials: every two-hop execution (uniform / Zipf /
  one-hot keys, dict-strings + nulls, worlds 4 and 8, joins, groupby)
  must match the ``CYLON_TPU_NO_TOPO`` flat oracle row-for-row;
- the per-axis traced counters (``shuffle.coll_bytes.{intra,inter,
  inter_alt}``) and the locality-clustered cross-outer reduction the
  decomposition exists for;
- gate discipline: flat 1-D contexts stay byte-identical and counter-
  clean, the kill switch re-fingerprints, repeated dispatch does not
  recompile, a tight outer budget re-plans without changing results;
- the relay ladder: same-outer-group skew tails ride the device
  ppermute ring (``shuffle.relay.ring_rows``), and the result still
  matches the flat oracle exactly;
- the ``hop_mode`` autopilot proposal math.
"""
import os

import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.engine import round_cap
from cylon_tpu.parallel import topo as _topo
from cylon_tpu.utils.tracing import report, reset_trace


def _ctx(devices, world, mesh=None):
    return ct.CylonContext.init_distributed(
        ct.TPUConfig(devices=devices[:world], mesh_shape=mesh)
    )


@pytest.fixture(scope="module")
def tctx(devices):
    """The canonical 4x2 topology context (8 devices)."""
    return _ctx(devices, 8, "4x2")


def _sorted_frame(t, cols):
    return (
        t.to_pandas()
        .sort_values(cols)
        .reset_index(drop=True)
    )


def _assert_tables_equal(got, want, cols):
    gp, wp = _sorted_frame(got, cols), _sorted_frame(want, cols)
    assert len(gp) == len(wp)
    for c in gp.columns:
        g, w = gp[c].to_numpy(), wp[c].to_numpy()
        if g.dtype.kind == "f":
            assert np.allclose(g, w, equal_nan=True), c
        else:
            assert np.array_equal(g, w), c


# ----------------------------------------------------------------------
# host planning units
# ----------------------------------------------------------------------
def test_parse_mesh():
    assert _topo.parse_mesh("", 8) is None
    assert _topo.parse_mesh("4x2", 8) == _topo.Topology(4, 2)
    assert _topo.parse_mesh(" 2X4 ", 8) == _topo.Topology(2, 4)
    # degenerate factors parse (effective() collapses them to flat)
    assert _topo.parse_mesh("8x1", 8) == _topo.Topology(8, 1)
    with pytest.raises(ValueError, match="expected 'OxI'"):
        _topo.parse_mesh("4", 8)
    with pytest.raises(ValueError, match="non-integer"):
        _topo.parse_mesh("ax2", 8)
    with pytest.raises(ValueError, match="!= world size"):
        _topo.parse_mesh("4x2", 16)


def test_group_tables_and_ring_perm():
    t = _topo.Topology(4, 2)
    assert _topo.inner_groups(t) == ((0, 1), (2, 3), (4, 5), (6, 7))
    assert _topo.outer_groups(t) == ((0, 2, 4, 6), (1, 3, 5, 7))
    # every device forwards to its next group-mate, wrapping per group
    assert _topo.ring_perm(t) == (
        (0, 1), (1, 0), (2, 3), (3, 2), (4, 5), (5, 4), (6, 7), (7, 6),
    )


def test_plan_two_hop_exact_capacity(rng):
    t = _topo.Topology(4, 2)
    world, cap = 8, 64
    counts = rng.integers(0, 2 * cap, (world, world)).astype(np.int64)
    k = int(-(-counts.max() // cap))
    plan = _topo.plan_two_hop(counts, t, cap, k, 1)
    agg = _topo.hop2_window_counts(counts, t, cap, k)
    # exact: the pow2 round-up of the true max, never an overflow
    assert plan.cap_o == round_cap(int(agg.max()))
    assert agg.max() <= plan.cap_o <= t.inner * round_cap(cap)
    # same-outer-group aggregates are zeroed (final after hop 1)
    w4 = agg.reshape(k, 4, 2, 4)
    for g in range(4):
        assert w4[:, g, :, g].sum() == 0


def test_axis_coll_bytes_formulas():
    t = _topo.Topology(4, 2)
    world, cap, k, rb, h = 8, 64, 2, 12, 1
    rows = cap + h
    # no topology: everything is "inter" by convention
    assert _topo.axis_coll_bytes(None, world, cap, k, rb, h) == (
        0, k * world * (world - 1) * rows * rb,
    )
    # flat-on-2D (1-hop forced): per-axis split of the flat exchange
    intra, inter = _topo.axis_coll_bytes(t, world, cap, k, rb, h)
    assert intra == k * world * (t.inner - 1) * rows * rb
    assert inter == k * world * (world - t.inner) * rows * rb
    # two-hop: the outer hop ships (outer-1) COMBINED chunks of cap_o
    cap_o = 128
    intra2, inter2 = _topo.axis_coll_bytes(
        t, world, cap, k, rb, h, cap_o=cap_o
    )
    assert intra2 == k * world * (t.inner - 1) * t.outer * rows * rb
    assert inter2 == k * world * (t.outer - 1) * (cap_o + h) * rb
    # the decomposition's point: fewer, larger cross-outer messages —
    # at equal payload the padded-chunk overhead drops from
    # (P - inner) chunks to (outer - 1)
    assert inter2 < inter


def test_split_relay_and_ring_sizing():
    t = _topo.Topology(2, 2)
    m = np.zeros((4, 4), np.int64)
    m[0, 1] = 30   # same outer group (devices 0,1)
    m[0, 2] = 50   # cross-group
    m[3, 2] = 7    # same group (devices 2,3)
    intra, inter = _topo.split_relay(m, t)
    assert intra[0, 1] == 30 and intra[3, 2] == 7 and intra.sum() == 37
    assert inter[0, 2] == 50 and inter.sum() == 50
    assert _topo.ring_cap(intra) == round_cap(30)
    # empty sides collapse to None
    assert _topo.split_relay(np.zeros((4, 4), np.int64), t) == (None, None)
    only_inter = np.zeros((4, 4), np.int64)
    only_inter[0, 2] = 5
    a, b = _topo.split_relay(only_inter, t)
    assert a is None and b is not None


def test_effective_collapses_degenerate(devices):
    flat = _ctx(devices, 8)
    assert _topo.effective(flat) is None
    deg = _ctx(devices, 8, "8x1")
    assert _topo.effective(deg) is None
    two = _ctx(devices, 8, "2x4")
    assert _topo.effective(two) == _topo.Topology(2, 4)
    with _topo.disabled():
        assert _topo.effective(two) is None


# ----------------------------------------------------------------------
# exact differentials vs the flat oracle
# ----------------------------------------------------------------------
def _key_values(rng, dist, n):
    if dist == "uniform":
        return rng.integers(0, 500, n).astype(np.int32)
    if dist == "zipf":
        return np.minimum(rng.zipf(1.3, n), 499).astype(np.int32)
    return np.zeros(n, np.int32)  # one-hot


@pytest.mark.parametrize("mesh,world", [("4x2", 8), ("2x4", 8), ("2x2", 4)])
@pytest.mark.parametrize("dist", ["uniform", "zipf", "onehot"])
def test_join_matches_flat_oracle(devices, mesh, world, dist):
    rng = np.random.default_rng(11)
    ctx = _ctx(devices, world, mesh)
    n = 1500
    lt = ct.Table.from_pydict(
        ctx,
        {"k": _key_values(rng, dist, n),
         "v": rng.normal(size=n).astype(np.float32)},
    )
    rt = ct.Table.from_pydict(
        ctx,
        {"k": _key_values(rng, dist, n // 2),
         "w": rng.normal(size=n // 2).astype(np.float32)},
    )
    got = lt.distributed_join(rt, on="k", how="inner")
    with _topo.disabled():
        want = lt.distributed_join(rt, on="k", how="inner")
    _assert_tables_equal(got, want, ["k_x", "v", "w"])


def test_strings_nulls_groupby_sort_match_oracle(devices):
    """Dict-encoded string keys with nulls through shuffle, groupby and
    distributed_sort on a 2x4 mesh — all exact vs the flat oracle."""
    rng = np.random.default_rng(5)
    ctx = _ctx(devices, 8, "2x4")
    n = 2000
    words = np.array(
        ["alpha", "beta", "gamma", "delta", "epsilon", "zeta", None] * 40,
        dtype=object,
    )
    df = pd.DataFrame(
        {
            "s": words[rng.integers(0, len(words), n)],
            "k": rng.integers(0, 60, n).astype(np.int32),
            "v": np.where(
                rng.random(n) < 0.1, np.nan, rng.normal(size=n)
            ).astype(np.float32),
        }
    )
    t = ct.Table.from_pandas(ctx, df)
    got_s = t.shuffle(["s"])
    got_g = t.distributed_groupby("k", {"v": "sum"})
    got_o = t.distributed_sort(["k"])
    with _topo.disabled():
        want_s = t.shuffle(["s"])
        want_g = t.distributed_groupby("k", {"v": "sum"})
        want_o = t.distributed_sort(["k"])
    assert got_s.row_count == want_s.row_count == n
    assert (got_s.row_counts == want_s.row_counts).all()
    _assert_tables_equal(got_g, want_g, ["k"])
    # distributed_sort: identical global order
    gp = got_o.to_pandas()["k"].to_numpy()
    wp = want_o.to_pandas()["k"].to_numpy()
    assert np.array_equal(gp, wp)


# ----------------------------------------------------------------------
# per-axis byte ledger + the locality win
# ----------------------------------------------------------------------
def test_per_axis_counters_and_killswitch_clean(devices, rng):
    ctx = _ctx(devices, 8, "4x2")
    t = ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(0, 300, 3000).astype(np.int32),
         "v": rng.normal(size=3000).astype(np.float32)},
    )
    reset_trace()
    t.shuffle(["k"])
    r = report("shuffle.")
    intra = int(r["shuffle.coll_bytes.intra"]["rows"])
    inter = int(r["shuffle.coll_bytes.inter"]["rows"])
    # both axes moved bytes, and the total IS the exchanged ledger
    assert intra > 0 and inter > 0
    assert intra + inter == int(r["shuffle.exchanged_bytes"]["rows"])
    # the other mode's cross-outer bytes ride beside them (the one-run
    # differential tools/topo_smoke.py gates on)
    assert int(r["shuffle.coll_bytes.inter_alt"]["rows"]) > 0
    # kill switch: counter-clean — the per-axis ledger never moves, the
    # byte-identical-to-1-D acceptance check
    reset_trace()
    with _topo.disabled():
        t.shuffle(["k"])
    rb = report("shuffle.")
    assert "shuffle.coll_bytes.intra" not in rb
    assert "shuffle.coll_bytes.inter" not in rb
    assert "shuffle.coll_bytes.inter_alt" not in rb


def test_flat_1d_context_counter_clean(devices, rng):
    """A context with NO topology keeps today's exchange: same rounds,
    same exchanged bytes, no per-axis counters — with the topo module
    enabled and with it killed."""
    ctx = _ctx(devices, 8)
    t = ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(0, 300, 3000).astype(np.int32),
         "v": rng.normal(size=3000).astype(np.float32)},
    )
    reset_trace()
    t.shuffle(["k"])
    r_on = report("shuffle.")
    reset_trace()
    with _topo.disabled():
        t.shuffle(["k"])
    r_off = report("shuffle.")
    assert "shuffle.coll_bytes.intra" not in r_on
    assert "shuffle.coll_bytes.inter" not in r_on
    for key in ("shuffle.rounds", "shuffle.exchanged_bytes"):
        assert r_on[key]["rows"] == r_off[key]["rows"]


def _locality_shards(rng, world, inner, n_shard, own_frac=0.8):
    """Per-shard key arrays where ``own_frac`` of each shard's keys hash
    to its OWN outer group — the workload shape (grouped ingest, range-
    loaded partitions) whose cross-outer traffic the two-hop exchange
    collapses. Pools come from the engine's own partitioner so the test
    can never drift from the routing hash."""
    import jax.numpy as jnp

    from cylon_tpu.ops.partition import hash_partition_ids

    cand = np.arange(20000, dtype=np.int32)
    pid = np.asarray(
        hash_partition_ids(
            [(jnp.asarray(cand), None)], jnp.int32(len(cand)), world
        )
    )
    outer = world // inner
    pools = [cand[(pid // inner) == g] for g in range(outer)]
    shards = []
    for p in range(world):
        own = rng.choice(pools[p // inner], size=int(n_shard * own_frac))
        other = rng.choice(cand, size=n_shard - len(own))
        shards.append(np.concatenate([own, other]).astype(np.int32))
    return shards


def test_locality_cross_outer_reduction(devices):
    """The headline saving: on locality-clustered keys (80% own-group)
    the two-hop cross-outer bytes land >= 25% under the flat oracle's —
    read from ONE run via the inter/inter_alt counter pair — at an
    exactly equal result."""
    rng = np.random.default_rng(23)
    ctx = _ctx(devices, 8, "4x2")
    keys = _locality_shards(rng, 8, 2, 2048)
    shards = [
        {"k": ks, "v": rng.normal(size=len(ks)).astype(np.float32)}
        for ks in keys
    ]
    t = ct.Table.from_shards(ctx, shards)
    reset_trace()
    got = t.shuffle(["k"])
    r = report("shuffle.")
    inter = int(r["shuffle.coll_bytes.inter"]["rows"])
    inter_flat = int(r["shuffle.coll_bytes.inter_alt"]["rows"])
    assert inter <= 0.75 * inter_flat, (inter, inter_flat)
    with _topo.disabled():
        want = t.shuffle(["k"])
    assert got.row_count == want.row_count
    assert (got.row_counts == want.row_counts).all()
    _assert_tables_equal(got, want, ["k", "v"])


# ----------------------------------------------------------------------
# gate discipline
# ----------------------------------------------------------------------
def test_gate_state_in_fingerprint(devices, rng):
    from cylon_tpu.plan.lazy import gated_fingerprint

    ctx = _ctx(devices, 8, "4x2")
    t = ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(0, 50, 200).astype(np.int32),
         "v": rng.normal(size=200).astype(np.float32)},
    )
    lf = t.lazy().filter(ct.col("v") > 0.0)
    fp_on = gated_fingerprint(lf.plan)
    with _topo.disabled():
        fp_off = gated_fingerprint(lf.plan)
    assert fp_on != fp_off
    # the component is topo.gate_state(): (kill switch, raw mesh request)
    assert _topo.gate_state() == (True, os.environ.get("CYLON_TPU_MESH", ""))
    prev = os.environ.get("CYLON_TPU_MESH")
    os.environ["CYLON_TPU_MESH"] = "4x2"
    try:
        assert _topo.gate_state() == (True, "4x2")
        fp_env = gated_fingerprint(lf.plan)
        assert fp_env != fp_off
    finally:
        if prev is None:
            os.environ.pop("CYLON_TPU_MESH", None)
        else:
            os.environ["CYLON_TPU_MESH"] = prev


def test_repeat_dispatch_no_recompile(tctx, rng):
    """Same shape + same plan: the second two-hop shuffle reuses every
    cached kernel (the TwoHopPlan tuple in the dispatch key is stable)."""
    t = ct.Table.from_pydict(
        tctx,
        {"k": rng.integers(0, 300, 3000).astype(np.int32),
         "v": rng.normal(size=3000).astype(np.float32)},
    )
    t.shuffle(["k"])
    before = len(tctx.__dict__.get("_jit_cache", {}))
    t.shuffle(["k"])
    assert len(tctx.__dict__.get("_jit_cache", {})) == before


def test_outer_budget_replans_exact(devices):
    """A tight cross-outer byte budget forces more, smaller rounds (the
    halving clamp) — the result stays exact vs the unclamped run."""
    rng = np.random.default_rng(31)
    ctx = _ctx(devices, 8, "4x2")
    t = ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(0, 300, 4000).astype(np.int32),
         "v": rng.normal(size=4000).astype(np.float32)},
    )
    reset_trace()
    base = t.shuffle(["k"])
    k0 = int(report("shuffle.")["shuffle.rounds"]["rows"])
    prev = os.environ.get("CYLON_TPU_OUTER_BUDGET")
    os.environ["CYLON_TPU_OUTER_BUDGET"] = "2048"
    try:
        reset_trace()
        got = t.shuffle(["k"])
        k1 = int(report("shuffle.")["shuffle.rounds"]["rows"])
    finally:
        if prev is None:
            os.environ.pop("CYLON_TPU_OUTER_BUDGET", None)
        else:
            os.environ["CYLON_TPU_OUTER_BUDGET"] = prev
    assert k1 > k0
    assert (got.row_counts == base.row_counts).all()
    _assert_tables_equal(got, base, ["k", "v"])


# ----------------------------------------------------------------------
# the relay ladder: device-direct ring for same-group tails
# ----------------------------------------------------------------------
def test_ring_relay_engages_and_matches_oracle(devices):
    """One-hot skew on a 4x2 mesh: the same-outer-group tail rides the
    inner-axis ppermute ring (device-direct, no host crossing) and the
    shuffle still matches the flat oracle exactly."""
    ctx = _ctx(devices, 8, "4x2")
    n = 2048
    t = ct.Table.from_pydict(
        ctx,
        {"k": np.zeros(n, np.int32),
         "v": np.arange(n, dtype=np.float32)},
    )
    reset_trace()
    s = t.shuffle(["k"])
    r = report("shuffle.")
    assert int(r["shuffle.relay.ring_rows"]["rows"]) > 0
    with _topo.disabled():
        base = t.shuffle(["k"])
    assert s.row_count == base.row_count == n
    assert (s.row_counts == base.row_counts).all()
    assert np.array_equal(
        np.sort(s.to_pandas()["v"].to_numpy()),
        np.sort(base.to_pandas()["v"].to_numpy()),
    )


# ----------------------------------------------------------------------
# the hop_mode autopilot proposal
# ----------------------------------------------------------------------
def test_hop_mode_proposal_math():
    from cylon_tpu.plan import feedback as fb

    # two-hop saving real (i2 well under i1): keep the default (None)
    p = {"hop_n": 4, "hop_i2_sum": 400, "hop_i1_sum": 4000}
    assert fb._hop_mode_proposal(p, 0.1) == (None, True)
    # two-hop NOT paying (i2 >= i1 within margin): force 1-hop
    p = {"hop_n": 4, "hop_i2_sum": 4000, "hop_i1_sum": 4000}
    assert fb._hop_mode_proposal(p, 0.1) == ("1hop", True)
    # degenerate observation: no decision
    assert fb._hop_mode_proposal({"hop_n": 0}, 0.1) == (None, True)


def test_decisions_tuple_back_compat():
    """Persisted 6-tuples (pre-topology stores) rehydrate with
    hop_mode=None — the trailing-field discipline."""
    from cylon_tpu.plan import feedback as fb

    old = (None, None, None, None, None, None)
    d = fb.Decisions(*old)
    assert d.hop_mode is None
    assert fb.Decisions(*(old + ("1hop",))).hop_mode == "1hop"


def test_prof_per_axis_stage_clocks():
    """The critical-path profiler splits the collective clock per axis
    under a two-hop plan and keeps the flat track without one."""
    from cylon_tpu.obs import prof

    counts = np.full((8, 8), 10, np.int64)
    flat = prof.shuffle_units([(counts, 1, 16, None, None)], 8)
    assert flat["collective"].sum() > 0
    # zero tracks are dropped from the ledger entirely
    assert "coll_inner" not in flat and "coll_outer" not in flat
    two = prof.shuffle_units([(counts, 1, 16, None, (4, 2, 32, 1))], 8)
    assert "collective" not in two
    assert two["coll_inner"].sum() > 0 and two["coll_outer"].sum() > 0
