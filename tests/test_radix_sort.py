"""Width-adaptive radix sort engine tests.

Layers, mirroring test_lane_pack.py:
  1. engine unit — digit lane planning (span/bias hints, float decline),
     pass census arithmetic, and the stable single-pass kernel against
     numpy on raw lanes;
  2. differential — every consumer shape (multi-key sort incl. NaN-last
     and descending floats, null sentinels, dictionary string codes,
     straddled >32-bit fused sort words, unique, groupby, join,
     shuffle) in EXACT emitted order against the CYLON_TPU_NO_RADIX=1
     bitonic oracle at worlds {1, 4, 8} — the stable lexsort
     permutation is unique, so order equality is the contract, not
     row-set equality;
  3. selection — the impl tag recompiles (never aliases) across
     CYLON_TPU_SORT_IMPL flips, and the forced Pallas tier (interpret
     mode on CPU) emits the same permutation.
"""
import os
import sys

import numpy as np
import pandas as pd
import pandas.testing as pdt
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp

import cylon_tpu as ct
from cylon_tpu.ops import radix as rx


@pytest.fixture(scope="module")
def ctx1(devices):
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:1]))


def _ctx(devices, world):
    return ct.CylonContext.init_distributed(
        ct.TPUConfig(devices=devices[:world])
    )


def _emitted_equal(got, want):
    """Exact emitted-order equality (no re-sort: a stability or
    permutation bug must not be masked by canonicalization)."""
    g = got.to_pandas().reset_index(drop=True)
    w = want.to_pandas().reset_index(drop=True)
    pdt.assert_frame_equal(g, w)


def _oracle(fn):
    with rx.disabled():
        return fn()


# ---------------------------------------------------------------------------
# 1. engine unit
# ---------------------------------------------------------------------------

def test_pass_census_arithmetic():
    assert rx.passes_for_spans([(0, 20)]) == 5
    assert rx.passes_for_spans([(19, 64)]) == 12  # the 3-key packed word
    assert rx.passes_for_spans([(0, 1)]) == 1
    assert rx.passes_for_spans([(0, 8)], impl="radix_pallas") == 1
    assert rx.bitonic_passes(1024, 1) == 55
    assert rx.bitonic_passes(1024, 3) == 165


def test_plan_declines_float_lanes():
    lanes = [jnp.zeros(8, jnp.float32), jnp.zeros(8, jnp.uint32)]
    assert rx.plan_lanes(lanes, None) is None


def test_single_pass_stable_vs_numpy(rng):
    n = 513
    lane = jnp.asarray(rng.integers(0, 16, n), jnp.uint32)
    perm = jnp.arange(n, dtype=jnp.int32)
    got = np.asarray(rx.radix_pass(lane, perm, 0, 4))
    want = np.argsort(np.asarray(lane), kind="stable")
    np.testing.assert_array_equal(got, want)


def test_lexsort_perm_matches_numpy_lexsort(rng):
    n = 700
    a = rng.integers(0, 50, n).astype(np.uint32)
    b = rng.integers(0, 1000, n).astype(np.uint32)
    # lanes least-significant first (the ops/sort.py convention)
    perm = rx.lexsort_perm(
        [jnp.asarray(b), jnp.asarray(a)], n,
        [rx.span_hint(0, 10), rx.span_hint(0, 6)],
    )
    assert perm is not None
    np.testing.assert_array_equal(np.asarray(perm), np.lexsort((b, a)))


# ---------------------------------------------------------------------------
# 2. differential vs the bitonic oracle, exact emitted order
# ---------------------------------------------------------------------------

def _sort_pair(ctx, df, keys, **kw):
    got = ct.Table.from_pandas(ctx, df).sort(keys, **kw)
    want = _oracle(lambda: ct.Table.from_pandas(ctx, df).sort(keys, **kw))
    _emitted_equal(got, want)


@pytest.mark.parametrize("world", [1, 4, 8])
def test_nan_last_floats(world, devices, rng):
    n = 900
    vals = rng.normal(size=n).astype(np.float64)
    vals[rng.random(n) < 0.15] = np.nan
    df = pd.DataFrame({
        "g": rng.integers(0, 12, n).astype(np.int32),
        "f": vals,
        "v": np.arange(n, dtype=np.int64),
    })
    # float key lanes make the digit planner decline; the int prefix
    # still radix-sorts when fused plans split — either way the emitted
    # order (NaN last within each group) must equal the oracle's
    _sort_pair(_ctx(devices, world), df, ["g", "f"])


@pytest.mark.parametrize("world", [1, 4, 8])
def test_descending_floats(world, devices, rng):
    n = 800
    vals = rng.normal(size=n).astype(np.float32)
    vals[rng.random(n) < 0.1] = np.nan
    df = pd.DataFrame({
        "f": vals,
        "k": rng.integers(-40, 40, n).astype(np.int32),
        "v": np.arange(n, dtype=np.int64),
    })
    _sort_pair(_ctx(devices, world), df, ["f", "k"],
               ascending=[False, False])


@pytest.mark.parametrize("world", [1, 4, 8])
def test_null_sentinels(world, devices, rng):
    n = 1000
    k1 = rng.integers(0, 30, n).astype(object)
    k1[rng.random(n) < 0.2] = None
    k2 = rng.integers(-500, 500, n).astype(object)
    k2[rng.random(n) < 0.2] = None
    df = pd.DataFrame({"k1": k1, "k2": k2,
                       "v": np.arange(n, dtype=np.int64)})
    _sort_pair(_ctx(devices, world), df, ["k1", "k2"])


@pytest.mark.parametrize("world", [1, 4, 8])
def test_dict_codes(world, devices, rng):
    n = 900
    words = np.array([f"w{i:03d}" for i in range(40)], dtype=object)
    k = rng.choice(words, n)
    k[rng.random(n) < 0.1] = None
    df = pd.DataFrame({
        "s": k,
        "k": rng.integers(0, 9, n).astype(np.int8),
        "v": np.arange(n, dtype=np.int64),
    })
    _sort_pair(_ctx(devices, world), df, ["s", "k"],
               ascending=[True, False])


@pytest.mark.parametrize("world", [1, 4, 8])
def test_straddled_64bit_fused_word(world, devices, rng):
    # ~20+16+7 key bits + null/pad lanes fuse into ONE uint64 sort word
    # whose lanes straddle the 32-bit boundary: the pass loop must walk
    # digit windows across the full 64-bit width
    n = 1100
    df = pd.DataFrame({
        "a": rng.integers(0, 1_000_000, n).astype(np.int32),
        "b": rng.integers(0, 60_000, n).astype(np.int32),
        "c": rng.integers(0, 120, n).astype(np.int32),
        "v": np.arange(n, dtype=np.int64),
    })
    ctx = _ctx(devices, world)
    _sort_pair(ctx, df, ["a", "b", "c"])
    _sort_pair(ctx, df, ["a", "b", "c"], ascending=[True, False, True])


@pytest.mark.parametrize("world", [1, 4, 8])
def test_unique_groupby_join_shuffle(world, devices, rng):
    n = 800
    df = pd.DataFrame({
        "k": rng.integers(0, 60, n).astype(np.int32),
        "j": rng.integers(-9, 9, n).astype(np.int64),
        "v": rng.normal(size=n).astype(np.float32),
    })
    rdf = pd.DataFrame({
        "k": rng.integers(0, 60, n // 2).astype(np.int32),
        "w": rng.normal(size=n // 2).astype(np.float32),
    })
    ctx = _ctx(devices, world)

    def build():
        t = ct.Table.from_pandas(ctx, df)
        r = ct.Table.from_pandas(ctx, rdf)
        u = t.unique(["k", "j"])
        g = t.distributed_groupby(["k", "j"], {"v": "sum"})
        j = t.distributed_join(r, on="k", how="inner")
        out = [u, g, j]
        if world > 1:
            out.append(t.shuffle(["k"]))
        return out

    got = build()
    want = _oracle(build)
    for g, w in zip(got, want):
        _emitted_equal(g, w)


# ---------------------------------------------------------------------------
# 3. impl selection
# ---------------------------------------------------------------------------

def test_impl_tag_recompiles_never_aliases(ctx1, rng, monkeypatch):
    n = 600
    df = pd.DataFrame({
        "a": rng.integers(0, 4000, n).astype(np.int32),
        "v": np.arange(n, dtype=np.int64),
    })
    t = ct.Table.from_pandas(ctx1, df)
    cache = ctx1.__dict__.setdefault("_jit_cache", {})
    monkeypatch.setenv("CYLON_TPU_SORT_IMPL", "radix")
    want = t.sort(["a"]).to_pandas()
    n0 = len(cache)
    monkeypatch.setenv("CYLON_TPU_SORT_IMPL", "bitonic")
    got = t.sort(["a"]).to_pandas()
    assert len(cache) == n0 + 1  # the flip compiled its OWN program
    pdt.assert_frame_equal(got, want)
    monkeypatch.setenv("CYLON_TPU_SORT_IMPL", "radix")
    t.sort(["a"]).to_pandas()
    assert len(cache) == n0 + 1  # flip-back reused the cached program


def test_forced_pallas_tier_matches(ctx1, rng, monkeypatch):
    n = 1024  # TILE-aligned: the Pallas pass engages (interpret on CPU)
    df = pd.DataFrame({
        "a": rng.integers(0, 1 << 16, n).astype(np.int32),
        "b": rng.integers(0, 1 << 12, n).astype(np.int32),
        "v": np.arange(n, dtype=np.int64),
    })
    t = ct.Table.from_pandas(ctx1, df)
    monkeypatch.setenv("CYLON_TPU_SORT_IMPL", "radix_pallas")
    got = t.sort(["a", "b"])
    monkeypatch.delenv("CYLON_TPU_SORT_IMPL")
    want = _oracle(lambda: ct.Table.from_pandas(ctx1, df).sort(["a", "b"]))
    _emitted_equal(got, want)


def test_kill_switch_forces_bitonic(ctx1, rng, monkeypatch):
    monkeypatch.setenv("CYLON_TPU_NO_RADIX", "1")
    assert rx.resolved_impl() == "bitonic"
    monkeypatch.setenv("CYLON_TPU_SORT_IMPL", "radix")
    assert rx.resolved_impl() == "bitonic"  # kill-switch wins over force
