"""Round-3 surface-gap closures (VERDICT.md item 9):

- Table.applymap — per-element host UDF (reference pycylon Table.applymap,
  python/pycylon/data/table.pyx:2222-2240), incl. string-valued UDFs;
- Table.minmax — fused min+max, one program + one host fetch (reference
  compute::MinMax, compute/aggregates.cpp:82-121);
- CSVReadOptions breadth — na_values / ignore_empty_lines / column-type
  overrides (reference io/csv_read_config.hpp:30+).

(Threaded multi-file ingest — table.cpp:799-829 analog — is the
ThreadPoolExecutor in io/csv.py read_csv and is covered by
tests/test_io.py::test_read_csv_per_shard_files.)
"""
import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.io import CSVReadOptions, read_csv


# ---------------------------------------------------------------- applymap
def test_applymap_numeric(world_ctx):
    n = 23
    t = ct.Table.from_pydict(
        world_ctx,
        {"a": np.arange(n, dtype=np.int64), "b": np.linspace(0, 1, n).astype(np.float64)},
    )
    out = t.applymap(lambda x: x * 2)
    df = out.to_pandas()
    assert np.array_equal(df["a"].values, np.arange(n) * 2)
    assert np.allclose(df["b"].values, np.linspace(0, 1, n) * 2)
    # sharding is preserved: same per-shard row counts
    assert np.array_equal(out.row_counts, t.row_counts)


def test_applymap_string_udf(world_ctx):
    t = ct.Table.from_pydict(
        world_ctx, {"a": np.array([1, 2, 3, 4, 5], dtype=np.int64)}
    )
    out = t.applymap(lambda x: f"v{x}")
    assert out.to_pandas()["a"].tolist() == ["v1", "v2", "v3", "v4", "v5"]


def test_applymap_on_strings(local_ctx):
    t = ct.Table.from_pydict(
        local_ctx, {"s": np.array(["ab", "cde", "f"], dtype=object)}
    )
    out = t.applymap(len)
    assert out.to_pandas()["s"].tolist() == [2, 3, 1]


def test_from_list(local_ctx):
    t = ct.Table.from_list(local_ctx, ["a", "s"], [[1, 2, 3], ["x", "y", "z"]])
    df = t.to_pandas()
    assert df["a"].tolist() == [1, 2, 3]
    assert df["s"].tolist() == ["x", "y", "z"]


# ----------------------------------------------------------------- minmax
def test_minmax_matches_separate(world_ctx, rng):
    vals = rng.normal(size=301).astype(np.float32)
    t = ct.Table.from_pydict(world_ctx, {"v": vals})
    mn, mx = t.minmax("v")
    assert mn == pytest.approx(float(vals.min()))
    assert mx == pytest.approx(float(vals.max()))
    assert mn == pytest.approx(t.min("v"))
    assert mx == pytest.approx(t.max("v"))


def test_minmax_int_with_nulls(world_ctx):
    vals = np.array([5, None, -7, 3, None, 12], dtype=object)
    t = ct.Table.from_pydict(world_ctx, {"v": vals})
    mn, mx = t.minmax("v")
    assert (int(mn), int(mx)) == (-7, 12)


def test_minmax_dictionary_column(local_ctx):
    t = ct.Table.from_pydict(
        local_ctx, {"s": np.array(["pear", "apple", "zed"], dtype=object)}
    )
    mn, mx = t.minmax("s")
    assert (mn, mx) == ("apple", "zed")


# ------------------------------------------------------------ CSV options
def test_csv_na_values(tmp_path, local_ctx):
    p = str(tmp_path / "na.csv")
    with open(p, "w") as f:
        f.write("a,b\n1,x\nNA,y\n3,NA\n")
    t = read_csv(local_ctx, p, CSVReadOptions().na_values(["NA"]))
    df = t.to_pandas()
    assert np.isnan(df["a"].values[1])
    assert df["a"].values[2] == 3
    assert df["b"].values[2] is None or (
        isinstance(df["b"].values[2], float) and np.isnan(df["b"].values[2])
    )


def test_csv_ignore_empty_lines_false(tmp_path, local_ctx):
    p = str(tmp_path / "empty.csv")
    with open(p, "w") as f:
        f.write("a,b\n1,2\n\n3,4\n")
    kept = read_csv(
        local_ctx, p, CSVReadOptions().ignore_empty_lines(False).na_values([""])
    )
    skipped = read_csv(local_ctx, p)
    assert kept.row_count == 3  # the empty line becomes an all-null row
    assert skipped.row_count == 2


def test_csv_column_type_overrides(tmp_path, local_ctx):
    p = str(tmp_path / "typed.csv")
    with open(p, "w") as f:
        f.write("a,b\n1,2\n3,4\n")
    t = read_csv(
        local_ctx, p, CSVReadOptions().with_column_types({"a": np.float64})
    )
    df = t.to_pandas()
    assert df["a"].dtype == np.float64
    assert df["b"].dtype == np.int64
