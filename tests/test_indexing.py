"""Indexing subsystem tests (reference python/test/test_index.py patterns)."""
import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.frame import DataFrame


def _tbl(ctx, rng, n=40):
    df = pd.DataFrame(
        {
            "id": np.arange(n, dtype=np.int64),
            "k": rng.integers(0, 7, n),
            "v": rng.normal(size=n),
        }
    )
    return df, ct.Table.from_pandas(ctx, df)


def test_set_reset_index(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    assert t.index.is_range()
    ti = t.set_index("id")
    assert ti.index.name == "id"
    assert ti.reset_index().index.is_range()


def test_loc_value(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    ti = t.set_index("id")
    out = ti.loc[7].to_pandas()
    assert len(out) == 1 and out["id"].iloc[0] == 7
    out = ti.loc[[3, 5, 11]].to_pandas()
    assert sorted(out["id"].tolist()) == [3, 5, 11]


def test_loc_slice_inclusive(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    ti = t.set_index("id")
    out = ti.loc[10:15].to_pandas()
    assert sorted(out["id"].tolist()) == list(range(10, 16))  # inclusive
    out = ti.loc[10:15, ["id", "v"]]
    assert out.column_names == ["id", "v"]


def test_loc_missing_values(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    ti = t.set_index("id")
    out = ti.loc[[1000, 2000]].to_pandas()
    assert len(out) == 0


def test_loc_requires_index(ctx8, rng):
    _, t = _tbl(ctx8, rng)
    with pytest.raises(ValueError):
        t.loc[3]


def test_iloc_scalar_slice_list(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    out = t.iloc[5].to_pandas()
    assert len(out) == 1 and out["id"].iloc[0] == df.iloc[5]["id"]
    out = t.iloc[10:20].to_pandas()
    assert sorted(out["id"].tolist()) == df.iloc[10:20]["id"].tolist()
    out = t.iloc[[0, 3, 39]].to_pandas()
    assert sorted(out["id"].tolist()) == [0, 3, 39]
    out = t.iloc[-1].to_pandas()
    assert out["id"].iloc[0] == 39
    out = t.iloc[0:20:2].to_pandas()
    assert len(out) == 10


def test_string_index(ctx8, rng):
    df = pd.DataFrame({"s": ["a", "b", "c", "d"], "v": [1.0, 2.0, 3.0, 4.0]})
    t = ct.Table.from_pandas(ctx8, df).set_index("s")
    out = t.loc[["b", "d"]].to_pandas()
    assert sorted(out["s"].tolist()) == ["b", "d"]
    out = t.loc["zzz":"zzz"] if False else t.loc[["nope"]]
    assert out.row_count == 0


def test_dataframe_indexing(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    d = DataFrame(_table=t).set_index("id")
    out = d.loc[[2, 4]].to_pandas()
    assert sorted(out["id"].tolist()) == [2, 4]
    out = d.iloc[0:5].to_pandas()
    assert len(out) == 5


def test_loc_slice_missing_bound_string(ctx8):
    df = pd.DataFrame({"s": ["a", "b", "d"], "v": [1.0, 2.0, 3.0]})
    t = ct.Table.from_pandas(ctx8, df).set_index("s")
    out = t.loc["c":].to_pandas()
    assert sorted(out["s"].tolist()) == ["d"]
    out = t.loc[:"c"].to_pandas()
    assert sorted(out["s"].tolist()) == ["a", "b"]


def test_index_preserved_through_filter(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    ti = t.set_index("id")
    sub = ti.loc[[3, 5]]
    assert sub.index_name == "id"
    again = sub.loc[[5]].to_pandas()
    assert again["id"].tolist() == [5]


def test_iloc_duplicates_and_order(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    out = t.iloc[[3, 1, 1]].to_pandas()
    assert out["id"].tolist() == [3, 1, 1]


# ---------------------------------------------------------------------------
# dtype x unique/dup x access-mode sweep vs pandas (VERDICT round-2 item 10;
# reference mode matrix: indexing/indexer.cpp LocIndexer, 1160 LoC)
# ---------------------------------------------------------------------------
_DTYPE_KEYS = {
    "int64": np.array([10, 3, 7, 3, 25, 7, 14, 3], dtype=np.int64),
    "int32": np.array([10, 3, 7, 3, 25, 7, 14, 3], dtype=np.int32),
    "float64": np.array([1.5, -2.0, 0.5, -2.0, 9.25, 0.5, 4.0, -2.0]),
    "string": np.array(["pear", "ant", "fig", "ant", "zed", "fig", "kiwi", "ant"], dtype=object),
    # no bool: pandas itself parses a list of bool LABELS as a row mask, so
    # label-mode loc on a bool index is ambiguous by spec
}


def _sweep_frame(keys, unique):
    k = np.unique(keys) if unique == "unique" else keys
    return pd.DataFrame({"key": k, "v": np.arange(len(k), dtype=np.float64)})


@pytest.mark.parametrize("dtype", list(_DTYPE_KEYS))
@pytest.mark.parametrize("uniq", ["unique", "dup"])
def test_loc_mode_matrix(ctx8, dtype, uniq):
    df = _sweep_frame(_DTYPE_KEYS[dtype], uniq)
    pdi = df.set_index("key")
    t = ct.Table.from_pandas(ctx8, df).set_index("key")

    def got_frame(out):
        g = out.to_pandas()
        return g.set_index("key")["v"]

    # -- scalar value (all occurrences, index order) --
    label = df["key"].iloc[2 % len(df)]
    want = pdi.loc[[label], "v"]
    got = got_frame(t.loc[label])
    assert got.tolist() == want.tolist()

    # -- list (request order, duplicates expanded) --
    labels = [df["key"].iloc[0], df["key"].iloc[2 % len(df)], df["key"].iloc[0]]
    want = pdi.loc[labels, "v"]
    got = got_frame(t.loc[labels])
    assert got.tolist() == want.tolist()
    assert got.index.tolist() == want.index.tolist()

    # -- slice (inclusive; requires monotonic index like pandas) --
    dfs = df.sort_values("key", kind="mergesort").reset_index(drop=True)
    pdis = dfs.set_index("key")
    ts = ct.Table.from_pandas(ctx8, dfs).set_index("key")
    lo = dfs["key"].iloc[1]
    hi = dfs["key"].iloc[-2]
    want = pdis.loc[lo:hi, "v"]
    got = got_frame(ts.loc[lo:hi])
    assert got.tolist() == want.tolist()

    # -- boolean mask --
    mask = (np.arange(len(df)) % 2 == 0).tolist()
    want = pdi.loc[mask, "v"]
    got = got_frame(t.loc[mask])
    assert got.tolist() == want.tolist()


@pytest.mark.parametrize("uniq", ["unique", "dup"])
def test_loc_list_duplicate_index_expansion(ctx8, uniq):
    """Non-unique index: loc[list] repeats every matching row per requested
    label, labels in request order — exact pandas semantics."""
    df = _sweep_frame(_DTYPE_KEYS["int64"], uniq)
    pdi = df.set_index("key")
    t = ct.Table.from_pandas(ctx8, df).set_index("key")
    labels = [3, 7] if uniq == "dup" else [3, 7, 3]
    want = pdi.loc[labels, "v"]
    got = t.loc[labels].to_pandas()
    assert got["v"].tolist() == want.tolist()
    assert got["key"].tolist() == want.index.tolist()


def test_iloc_loc_empty_list(ctx8, rng):
    t = ct.Table.from_pydict(ctx8, {"a": rng.integers(0, 10, 40), "b": rng.normal(size=40)})
    assert t.iloc[[]].row_count == 0
    ti = t.set_index("a")
    assert ti.loc[[]].row_count == 0


def test_descending_nan_last_f32_and_f64(local_ctx):
    """Unmasked NaNs sort LAST in descending order for both f32 and f64 keys
    (ops/sort.py _norm_key NaN pinning)."""
    vals = np.array([3.0, np.nan, 1.0, 2.0])
    for dt in (np.float32, np.float64):
        t = ct.Table.from_pydict(local_ctx, {"x": vals.astype(dt)})
        out = np.asarray(t.sort("x", ascending=False).to_pandas()["x"])
        assert np.isnan(out[-1]), (dt, out)
        assert list(out[:3]) == [3.0, 2.0, 1.0], (dt, out)


# ---------------------------------------------------------------------------
# loc/iloc mode matrix + build-once HashIndex/LinearIndex
# (reference indexer.cpp 1160-LoC mode coverage; index.hpp:82 HashIndex,
# :395 LinearIndex). Oracle: pandas.
# ---------------------------------------------------------------------------

def _dup_tbl(ctx, rng, n=30):
    """Index with DUPLICATE entries + a string column."""
    df = pd.DataFrame(
        {
            "id": rng.integers(0, 10, n).astype(np.int64),
            "v": rng.normal(size=n),
            "s": rng.choice(["a", "b", "c"], n),
        }
    )
    return df, ct.Table.from_pandas(ctx, df)


def test_loc_bool_mask(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    ti = t.set_index("id")
    out = ti.loc[(ti["k"] > 3)].to_pandas()
    exp = df.set_index("id").loc[df.set_index("id")["k"] > 3].reset_index()
    assert sorted(out["id"].tolist()) == sorted(exp["id"].tolist())


def test_iloc_bool_mask(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    m = (df["k"] > 3).to_numpy()
    out = t.iloc[m].to_pandas()
    assert sorted(out["id"].tolist()) == sorted(df[m]["id"].tolist())


def test_iloc_negative_and_step(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    out = t.iloc[-5].to_pandas()
    assert out["id"].iloc[0] == df["id"].iloc[-5]
    out = t.iloc[2:20:3].to_pandas()
    assert out["id"].tolist() == df["id"].iloc[2:20:3].tolist()


def test_hash_index_build_and_reuse(ctx8, rng):
    df, t = _dup_tbl(ctx8, rng)
    ti = t.set_index("id")
    hi = ti.build_index("hash")
    assert ti.build_index("hash") is hi  # cached: build once, reuse
    pdf = df.set_index("id")
    # get_loc: all positions of a duplicated value
    positions = hi.get_loc(3)
    assert positions.tolist() == np.nonzero((df["id"] == 3).to_numpy())[0].tolist()
    assert (5 in hi) == bool((df["id"] == 5).any())
    assert 1000 not in hi


def test_hash_index_loc_list_duplicates_order(ctx8, rng):
    """pandas loc[list] returns rows in REQUEST order with duplicates
    expanded — only the built-index path can honor that."""
    df, t = _dup_tbl(ctx8, rng)
    ti = t.set_index("id")
    ti.build_index("hash")
    want = [7, 2, 7]
    out = ti.loc[want].to_pandas()
    exp = df.set_index("id").loc[want].reset_index()
    assert out["id"].tolist() == exp["id"].tolist()
    assert np.allclose(out["v"].to_numpy(), exp["v"].to_numpy())


def test_hash_index_missing_lenient_like_eager_path(ctx8, rng):
    """Missing labels are skipped identically with and without a built
    index — loc behavior must not flip based on the invisible index cache."""
    df, t = _dup_tbl(ctx8, rng)
    ti = t.set_index("id")
    assert ti.loc[[1000]].row_count == 0  # eager path
    ti.build_index("hash")
    assert ti.loc[[1000]].row_count == 0  # built-index path: same answer
    present = int(df["id"].iloc[0])
    assert ti.loc[[present, 1000]].row_count == int(
        (df["id"] == present).sum()
    )


def test_linear_index_parity(ctx8, rng):
    df, t = _dup_tbl(ctx8, rng)
    ti = t.set_index("id")
    li = ti.build_index("linear")
    hi_positions = ct.indexing.HashIndex(ti).loc_positions([4, 9])
    assert li.loc_positions([4, 9]).tolist() == hi_positions.tolist()


def test_string_hash_index(ctx8, rng):
    df, t = _dup_tbl(ctx8, rng)
    ts = t.set_index("s")
    hi = ts.build_index("hash")
    assert ("a" in hi) == bool((df["s"] == "a").any())
    out = ts.loc[["b"]].to_pandas()
    exp = df[df["s"] == "b"]
    assert len(out) == len(exp)


def test_setitem_invalidates_built_index(ctx8, rng):
    df, t = _dup_tbl(ctx8, rng)
    ti = t.set_index("id")
    ti.build_index("hash")
    old_hits = len(ti.loc[[3]].to_pandas()) if (df["id"] == 3).any() else 0
    ti["id"] = np.full(len(df), 3, np.int64)  # rewrite the index column
    out = ti.loc[[3]].to_pandas()
    assert len(out) == len(df), "stale built index served old positions"


def test_float_probe_on_int_index_no_alias(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    ti = t.set_index("id")
    ti.build_index("hash")
    with pytest.raises(KeyError):
        ti.loc[[3.5]]  # pandas raises; must NOT alias to id==3
    hi = ti.build_index("hash")
    assert 3.5 not in hi


def test_null_index_entries_unmatchable(ctx8):
    df = pd.DataFrame({"id": [1.0, np.nan, 2.0, np.nan, 1.0], "v": range(5)})
    t = ct.Table.from_pandas(ctx8, df).set_index("id")
    hi = t.build_index("hash")
    assert hi.get_loc(1.0).tolist() == [0, 4]
    # a null's garbage physical payload (0.0) must not be matchable
    assert 0.0 not in hi


def test_loc_iloc_bool_list(ctx8, rng):
    df, t = _tbl(ctx8, rng, n=8)
    ti = t.set_index("id")
    m = [True, False, False, True, False, True, False, False]
    out = ti.loc[m].to_pandas()
    exp = df[np.asarray(m)]
    assert sorted(out["id"].tolist()) == sorted(exp["id"].tolist())
    out2 = t.iloc[m].to_pandas()
    assert sorted(out2["id"].tolist()) == sorted(exp["id"].tolist())


def test_incompatible_probe_types(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    ti = t.set_index("id")
    hi = ti.build_index("hash")
    assert "a" not in hi  # pandas: False, not a numpy coercion error
    with pytest.raises(KeyError):
        hi.loc_positions(["a"])
