"""Indexing subsystem tests (reference python/test/test_index.py patterns)."""
import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.frame import DataFrame


def _tbl(ctx, rng, n=40):
    df = pd.DataFrame(
        {
            "id": np.arange(n, dtype=np.int64),
            "k": rng.integers(0, 7, n),
            "v": rng.normal(size=n),
        }
    )
    return df, ct.Table.from_pandas(ctx, df)


def test_set_reset_index(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    assert t.index.is_range()
    ti = t.set_index("id")
    assert ti.index.name == "id"
    assert ti.reset_index().index.is_range()


def test_loc_value(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    ti = t.set_index("id")
    out = ti.loc[7].to_pandas()
    assert len(out) == 1 and out["id"].iloc[0] == 7
    out = ti.loc[[3, 5, 11]].to_pandas()
    assert sorted(out["id"].tolist()) == [3, 5, 11]


def test_loc_slice_inclusive(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    ti = t.set_index("id")
    out = ti.loc[10:15].to_pandas()
    assert sorted(out["id"].tolist()) == list(range(10, 16))  # inclusive
    out = ti.loc[10:15, ["id", "v"]]
    assert out.column_names == ["id", "v"]


def test_loc_missing_values(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    ti = t.set_index("id")
    out = ti.loc[[1000, 2000]].to_pandas()
    assert len(out) == 0


def test_loc_requires_index(ctx8, rng):
    _, t = _tbl(ctx8, rng)
    with pytest.raises(ValueError):
        t.loc[3]


def test_iloc_scalar_slice_list(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    out = t.iloc[5].to_pandas()
    assert len(out) == 1 and out["id"].iloc[0] == df.iloc[5]["id"]
    out = t.iloc[10:20].to_pandas()
    assert sorted(out["id"].tolist()) == df.iloc[10:20]["id"].tolist()
    out = t.iloc[[0, 3, 39]].to_pandas()
    assert sorted(out["id"].tolist()) == [0, 3, 39]
    out = t.iloc[-1].to_pandas()
    assert out["id"].iloc[0] == 39
    out = t.iloc[0:20:2].to_pandas()
    assert len(out) == 10


def test_string_index(ctx8, rng):
    df = pd.DataFrame({"s": ["a", "b", "c", "d"], "v": [1.0, 2.0, 3.0, 4.0]})
    t = ct.Table.from_pandas(ctx8, df).set_index("s")
    out = t.loc[["b", "d"]].to_pandas()
    assert sorted(out["s"].tolist()) == ["b", "d"]
    out = t.loc["zzz":"zzz"] if False else t.loc[["nope"]]
    assert out.row_count == 0


def test_dataframe_indexing(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    d = DataFrame(_table=t).set_index("id")
    out = d.loc[[2, 4]].to_pandas()
    assert sorted(out["id"].tolist()) == [2, 4]
    out = d.iloc[0:5].to_pandas()
    assert len(out) == 5


def test_loc_slice_missing_bound_string(ctx8):
    df = pd.DataFrame({"s": ["a", "b", "d"], "v": [1.0, 2.0, 3.0]})
    t = ct.Table.from_pandas(ctx8, df).set_index("s")
    out = t.loc["c":].to_pandas()
    assert sorted(out["s"].tolist()) == ["d"]
    out = t.loc[:"c"].to_pandas()
    assert sorted(out["s"].tolist()) == ["a", "b"]


def test_index_preserved_through_filter(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    ti = t.set_index("id")
    sub = ti.loc[[3, 5]]
    assert sub.index_name == "id"
    again = sub.loc[[5]].to_pandas()
    assert again["id"].tolist() == [5]


def test_iloc_duplicates_and_order(ctx8, rng):
    df, t = _tbl(ctx8, rng)
    out = t.iloc[[3, 1, 1]].to_pandas()
    assert out["id"].tolist() == [3, 1, 1]


def test_iloc_loc_empty_list(ctx8, rng):
    t = ct.Table.from_pydict(ctx8, {"a": rng.integers(0, 10, 40), "b": rng.normal(size=40)})
    assert t.iloc[[]].row_count == 0
    ti = t.set_index("a")
    assert ti.loc[[]].row_count == 0


def test_descending_nan_last_f32_and_f64(local_ctx):
    """Unmasked NaNs sort LAST in descending order for both f32 and f64 keys
    (ops/sort.py _norm_key NaN pinning)."""
    vals = np.array([3.0, np.nan, 1.0, 2.0])
    for dt in (np.float32, np.float64):
        t = ct.Table.from_pydict(local_ctx, {"x": vals.astype(dt)})
        out = np.asarray(t.sort("x", ascending=False).to_pandas()["x"])
        assert np.isnan(out[-1]), (dt, out)
        assert list(out[:3]) == [3.0, 2.0, 1.0], (dt, out)
