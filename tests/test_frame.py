"""DataFrame/CylonEnv layer tests vs pandas oracles.

Reference analog: python/test/test_frame.py (construction equivalence),
test_dist_rl.py (distributed relational algebra via env kwarg).
"""
import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.frame import CylonEnv, DataFrame, concat


@pytest.fixture(scope="module")
def env(devices):
    return CylonEnv(config=ct.TPUConfig(devices=devices[:4]))


def _pair(rng, n=40, m=30, ks=12):
    a = pd.DataFrame({"id": rng.integers(0, ks, n), "x": rng.normal(size=n)})
    b = pd.DataFrame({"id": rng.integers(0, ks, m), "y": rng.normal(size=m)})
    return a, b


def test_construction_equivalence(env, rng):
    pdf = pd.DataFrame({"a": [1, 2, 3], "b": [0.1, 0.2, 0.3]})
    for data in (pdf, {"a": [1, 2, 3], "b": [0.1, 0.2, 0.3]}):
        df = DataFrame(data, ctx=env.context)
        pd.testing.assert_frame_equal(df.to_pandas(), pdf, check_dtype=False)


def test_merge_env_switch(env, rng):
    a, b = _pair(rng)
    da = DataFrame(a, ctx=env.context)
    db = DataFrame(b, ctx=env.context)
    got = da.merge(db, on="id", how="inner", env=env).to_pandas()
    exp = a.merge(b, on="id", how="inner")
    assert len(got) == len(exp)
    assert set(got.columns) == {"id", "x", "y"}
    cols = ["id", "x", "y"]
    pd.testing.assert_frame_equal(
        got.sort_values(cols).reset_index(drop=True)[cols],
        exp.sort_values(cols).reset_index(drop=True)[cols],
        check_dtype=False,
    )


@pytest.mark.parametrize("how", ["left", "right", "outer"])
def test_merge_outer_coalesce(env, rng, how):
    a, b = _pair(rng, ks=20)
    da = DataFrame(a, ctx=env.context)
    db = DataFrame(b, ctx=env.context)
    got = da.merge(db, on="id", how=how, env=env).to_pandas()
    exp = a.merge(b, on="id", how=how)
    assert len(got) == len(exp)
    # the coalesced key column must match pandas' key exactly (as a multiset)
    assert sorted(got["id"].tolist()) == sorted(exp["id"].tolist())


def test_sort_values(env, rng):
    a, _ = _pair(rng, n=77)
    da = DataFrame(a, ctx=env.context)
    got = da.sort_values("x", env=env).to_pandas()["x"].to_numpy()
    assert (np.diff(got) >= 0).all()
    got_local = da.sort_values("x").to_pandas()  # per-shard only
    assert len(got_local) == 77


def test_drop_duplicates(env, rng):
    a = pd.DataFrame({"k": rng.integers(0, 8, 60)})
    da = DataFrame(a, ctx=env.context)
    got = da.drop_duplicates(env=env).to_pandas()
    assert sorted(got["k"].tolist()) == sorted(a["k"].drop_duplicates().tolist())


def test_groupby_agg(env, rng):
    a, _ = _pair(rng, n=90)
    da = DataFrame(a, ctx=env.context)
    got = (
        da.groupby("id", env=env)
        .agg({"x": ["sum", "count"]})
        .to_pandas()
        .sort_values("id")
        .reset_index(drop=True)
    )
    exp = (
        a.groupby("id")["x"]
        .agg(["sum", "count"])
        .reset_index()
        .rename(columns={"sum": "x_sum", "count": "x_count"})
    )
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_groupby_convenience(env, rng):
    a, _ = _pair(rng, n=50)
    da = DataFrame(a, ctx=env.context)
    got = da.groupby("id", env=env).mean().to_pandas().sort_values("id").reset_index(drop=True)
    exp = a.groupby("id")["x"].mean().reset_index().rename(columns={"x": "x_mean"})
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_filter_operators(env, rng):
    a, _ = _pair(rng, n=64)
    da = DataFrame(a, ctx=env.context)
    mask = da["x"] > 0.0
    got = da[mask].to_pandas()
    exp = a[a["x"] > 0.0]
    assert len(got) == len(exp)
    np.testing.assert_allclose(
        np.sort(got["x"].to_numpy()), np.sort(exp["x"].to_numpy())
    )
    # compound masks
    m2 = (da["x"] > 0.0) & (da["id"] < 6)
    got2 = da[m2].to_pandas()
    exp2 = a[(a["x"] > 0.0) & (a["id"] < 6)]
    assert len(got2) == len(exp2)


def test_arithmetic(env, rng):
    a, _ = _pair(rng, n=32)
    da = DataFrame(a, ctx=env.context)
    out = (da["x"] * 2.0 + 1.0).to_pandas()["x"].to_numpy()
    np.testing.assert_allclose(np.sort(out), np.sort(a["x"].to_numpy() * 2 + 1))


def test_concat(env, rng):
    a, b = _pair(rng)
    b = b.rename(columns={"y": "x"})
    da = DataFrame(a, ctx=env.context)
    db = DataFrame(b, ctx=env.context)
    got = concat([da, db], env=env).to_pandas()
    exp = pd.concat([a, b])
    assert len(got) == len(exp)
    np.testing.assert_allclose(
        np.sort(got["x"].to_numpy()), np.sort(exp["x"].to_numpy())
    )


def test_fillna_isnull(env, rng):
    a = pd.DataFrame({"x": [1.0, np.nan, 3.0, np.nan]})
    da = DataFrame(a, ctx=env.context)
    assert da.isnull().to_pandas()["x"].tolist() == [False, True, False, True]
    filled = da.fillna(9.0).to_pandas()["x"].tolist()
    assert filled == [1.0, 9.0, 3.0, 9.0]


def test_env_properties(env):
    assert env.world_size == 4
    assert env.rank == 0
    env.barrier()


def test_frame_surface_completions(local_ctx, tmp_path):
    """add_prefix / isna / notna / to_arrow / to_csv / context / device
    helpers (reference frame.py:42-98, 217-227, 985)."""
    import numpy as np
    import pandas as pd

    df = ct.DataFrame(pd.DataFrame({"a": [1, 2, 3], "b": [1.0, np.nan, 3.0]}))
    pre = df.add_prefix("x_")
    assert pre.columns == ["x_a", "x_b"]
    assert df.isna().to_pandas()["b"].tolist() == [False, True, False]
    assert df.notna().to_pandas()["b"].tolist() == [True, False, True]
    at = df.to_arrow()
    assert at.column_names == ["a", "b"] and at.num_rows == 3
    p = str(tmp_path / "f.csv")
    df.to_csv(p)
    got = pd.read_csv(p)
    assert got["a"].tolist() == [1, 2, 3]
    assert df.context.world_size >= 1
    import jax

    assert df.is_cpu() == (jax.default_backend() == "cpu")
    assert df.is_device("cpu") == df.is_cpu()
    assert df.to_cpu() is df and df.to_device() is df
    # index follows add_prefix (pandas semantics)
    pre_idx = df.set_index("a").add_prefix("x_")
    assert pre_idx.table.index_name == "x_a"
