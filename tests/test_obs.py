"""Query-scoped telemetry (ISSUE 8): cylon_tpu/obs/.

Covers the tentpole surface end to end:

- span-TREE shape of a traced q3 collect (plan.node spans nested under
  plan.execute, node ids, per-query counters, device-resolved end);
- ``explain(analyze=True)`` golden assertions (per-node ms / rows /
  coll MB / gate decisions on the fused q3 shape);
- Chrome trace-event export: schema-validates and round-trips;
- DISABLED tracer allocates nothing (no Span / QueryTrace objects) and
  leaves the flight ring untouched;
- flight-recorder ring eviction under CYLON_TPU_TRACE_RING;
- fingerprint latency histograms (quantile math + the always-on
  dispatch observation path);
- every metric a q3 run emits is covered by the documented stable-name
  table (obs.metrics.STABLE_METRICS);
- two concurrent traced queries build DISJOINT trees while the
  process-global rollup keeps the cross-query sum;
- ``utils/tracing.profile()`` smoke (the jax.profiler passthrough).
"""
import gc
import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import cylon_tpu as ct
from cylon_tpu import col
from cylon_tpu.obs import export as obs_export
from cylon_tpu.obs import metrics as obs_metrics
from cylon_tpu.obs import trace as obs_trace
from cylon_tpu.utils import tracing


def _q3(ctx, rng, n=3000, salt=0.0):
    ta = ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(0, 40, n).astype(np.int32),
         "v": rng.normal(size=n).astype(np.float32)},
    )
    tb = ct.Table.from_pydict(
        ctx,
        {"rk": rng.integers(0, 40, n).astype(np.int32),
         "w": rng.normal(size=n).astype(np.float32)},
    )
    return (
        ta.lazy()
        .join(tb.lazy(), left_on="k", right_on="rk")
        .filter(col("w") > salt)
        .groupby("k", {"v": "sum"})
    )


@pytest.fixture
def traced(monkeypatch):
    """Structured tracing on (no stderr log), fresh ring."""
    monkeypatch.setenv("CYLON_TPU_TRACE", "tree")
    obs_export.reset_ring()
    yield
    obs_export.reset_ring()


# ----------------------------------------------------------------------
# span trees
# ----------------------------------------------------------------------
def test_q3_span_tree_shape(ctx8, rng, traced):
    lf = _q3(ctx8, rng)
    lf.collect()  # compile outside the assertion run
    obs_export.reset_ring()
    lf.collect()
    qs = [q for q in obs_export.traces() if q.kind == "plan"]
    assert len(qs) == 1
    q = qs[0]
    roots = [sp.name for sp in q.spans]
    assert roots == ["plan.optimize", "plan.lower", "plan.execute"]
    execute = q.spans[-1]
    # per-node spans nest under plan.execute, parent/child links intact:
    # the fused q3 node is the root, its Filter input nested below it
    names = [sp.name for sp in execute.walk()]
    assert "plan.node.FusedJoinGroupBySum" in names
    assert "plan.node.Filter" in names
    fused = next(
        sp for sp in execute.walk()
        if sp.name == "plan.node.FusedJoinGroupBySum"
    )
    assert any(
        c.name == "plan.node.Filter" for c in fused.walk()
    ), "input node must be a descendant of its consumer's span"
    assert isinstance(fused.attrs.get("node_id"), int)
    # per-query counters: the cache hit of this collect is attributed to
    # THIS query, not just the global blob
    assert q.counters["plan.cache.hit"][0] == 1
    # the span carries collective accounting from the pair shuffle
    assert fused.attrs.get("coll_bytes", 0) > 0
    assert q.hist_key, "dispatch must label the trace with the fingerprint"
    # device-resolved end time rode the deferred count fetch
    assert q.device_resolved_s() is not None
    assert q.resolved >= q.t0


def test_eager_chain_implicit_trace(ctx8, rng, traced):
    t = ct.Table.from_pydict(
        ctx8, {"k": rng.integers(0, 10, 512).astype(np.int32)}
    )
    obs_export.reset_ring()
    t.shuffle(["k"])
    ops = [q for q in obs_export.traces() if q.kind == "op"]
    assert ops, "an outermost eager span must open an implicit trace"
    names = {sp.name for q in ops for sp in q.all_spans()}
    assert "shuffle.exchange" in names


# ----------------------------------------------------------------------
# explain(analyze=True)
# ----------------------------------------------------------------------
def test_explain_analyze_golden_q3(ctx8, rng):
    lf = _q3(ctx8, rng, salt=0.111)
    text = lf.explain(analyze=True)
    assert "== Analyzed plan (executed) ==" in text
    # the fused node line carries measured time, rows in->out and coll MB
    fused_line = next(
        ln for ln in text.splitlines() if "FusedJoinGroupBySum" in ln
    )
    assert " ms (self " in fused_line
    assert "rows=" in fused_line and "->" in fused_line
    assert "coll=" in fused_line and "MB" in fused_line
    # gate decisions are printed per node; the plan-cache decision rides
    # the summary line (it fires before the trace opens)
    assert "gates[" in text
    assert "plan-cache hit" in text or "plan-cache miss" in text
    # scan rows are exact (analyze materializes every node)
    scan_line = next(
        ln for ln in text.splitlines()
        if "Scan [k, v]" in ln and "**" in ln
    )
    assert "rows=3000" in scan_line
    assert "Plan fingerprint: " in text
    assert "Rewrites fired: " in text
    # the default path is unchanged (no measurements, both plans shown)
    plain = lf.explain()
    assert "== Optimized plan ==" in plain and "**" not in plain
    # the analyzed run is diagnostic: its (per-node-synced, possibly
    # compile-laden) wall must NOT land in the fingerprint histogram
    # that serving p50/p99 reads
    obs_metrics.reset_latency()
    lf.explain(analyze=True)
    assert obs_metrics.latency_report() == {}


def test_explain_analyze_keeps_dispatch_sync_contract(devices, rng):
    """The analyzed run is diagnostic; the PRODUCTION dispatch path must
    still perform zero syncs at dispatch + one at materialization — the
    q3_dispatch contract shape: a 1-device mesh (serving: many
    concurrent single-replica queries), where the fused plan has no
    shuffle and the whole chain defers its count fetch."""
    from cylon_tpu.analysis.hostsync import sync_monitor

    ctx1 = ct.CylonContext.init_distributed(
        ct.TPUConfig(devices=devices[:1])
    )
    lf = _q3(ctx1, rng, salt=0.222)
    lf.explain(analyze=True)  # warm + analyzed (per-node syncs allowed)
    lf.collect()
    with sync_monitor() as events:
        t = lf.dispatch()
    assert events == [], [e.site for e in events]
    with sync_monitor() as events:
        t._materialize()
    assert [e.site for e in events] == ["_materialize_counts"]


# ----------------------------------------------------------------------
# Chrome export
# ----------------------------------------------------------------------
def test_chrome_export_schema_and_roundtrip(ctx8, rng, traced, tmp_path):
    lf = _q3(ctx8, rng)
    lf.collect()
    obs_export.reset_ring()
    lf.collect()
    lf.collect()
    qs = obs_export.traces()
    n_spans = sum(len(list(q.all_spans())) for q in qs)
    path = tmp_path / "trace.json"
    n_events = obs_export.write_chrome(str(path))
    doc = obs_export.load_chrome(str(path))
    assert obs_export.validate_chrome(doc) == []
    # per query: one thread_name metadata + one query event + its spans
    assert n_events == len(doc["traceEvents"]) == n_spans + 2 * len(qs)
    tracks = obs_export.summarize(doc)
    plan_tracks = [t for t in tracks.values() if t["name"].startswith("plan:")]
    assert len(plan_tracks) == 2
    for t in plan_tracks:
        assert t["spans"] > 0 and t["query_ms"] > 0
        assert t["args"].get("fingerprint")
    # raw-JSON round trip: what we wrote is what a Perfetto load parses
    assert json.loads(path.read_text())["displayTimeUnit"] == "ms"


# ----------------------------------------------------------------------
# disabled-tracer pins
# ----------------------------------------------------------------------
def test_disabled_tracer_allocates_nothing(ctx8, rng, monkeypatch):
    monkeypatch.delenv("CYLON_TPU_TRACE", raising=False)
    lf = _q3(ctx8, rng, salt=0.333)
    lf.collect()  # warm
    obs_export.reset_ring()
    gc.collect()
    before = sum(
        isinstance(o, (obs_trace.Span, obs_trace.QueryTrace))
        for o in gc.get_objects()
    )
    lf.collect()
    gc.collect()
    after = sum(
        isinstance(o, (obs_trace.Span, obs_trace.QueryTrace))
        for o in gc.get_objects()
    )
    assert after == before, "disabled tracer must allocate no trace objects"
    assert obs_export.traces() == []
    assert obs_trace.current() is None


def test_disabled_span_still_feeds_rollup(local_ctx):
    tracing.reset_trace()
    with tracing.span("unit.disabled", rows=7):
        pass
    rep = tracing.get_trace_report()
    assert rep["unit.disabled"]["count"] == 1
    assert rep["unit.disabled"]["rows"] == 7


# ----------------------------------------------------------------------
# flight ring
# ----------------------------------------------------------------------
def test_ring_eviction(ctx8, rng, traced, monkeypatch):
    monkeypatch.setenv("CYLON_TPU_TRACE_RING", "4")
    lf = _q3(ctx8, rng)
    lf.collect()
    obs_export.reset_ring()
    for _ in range(6):
        lf.collect()
    qs = obs_export.traces()
    assert len(qs) == 4, "ring must hold exactly CYLON_TPU_TRACE_RING traces"
    qids = [q.qid for q in qs]
    assert qids == sorted(qids), "oldest-first order"
    # the evicted traces are the two oldest (strictly increasing qids)
    assert qids[0] > 0 and len(set(qids)) == 4


# ----------------------------------------------------------------------
# latency histograms (the serving substrate)
# ----------------------------------------------------------------------
def test_histogram_quantiles_unit():
    h = obs_metrics.Histogram()
    for ms in range(1, 101):  # 1..100 ms uniform
        h.record(ms / 1e3)
    assert h.n == 100
    # geometric buckets: ~10% relative resolution at any quantile
    assert h.quantile(0.50) == pytest.approx(0.050, rel=0.15)
    assert h.quantile(0.99) == pytest.approx(0.099, rel=0.15)
    assert h.quantile(1.0) == pytest.approx(h.max_s)
    assert obs_metrics.Histogram().quantile(0.5) == 0.0


def test_dispatch_observes_fingerprint_histogram(ctx8, rng, monkeypatch):
    """Latency histograms fill WITHOUT tracing enabled: the serving
    metrics path is always on, and the end time rides the deferred
    materialization (no extra sync)."""
    monkeypatch.delenv("CYLON_TPU_TRACE", raising=False)
    obs_metrics.reset_latency()
    lf = _q3(ctx8, rng, salt=0.444)
    for _ in range(3):
        lf.collect()
    rep = obs_metrics.latency_report()
    [(key, ent)] = [
        (k, v) for k, v in rep.items() if "FusedJoinGroupBySum" in v["label"]
    ]
    assert ent["count"] == 3
    assert 0 < ent["p50_s"] <= ent["p95_s"] <= ent["p99_s"]
    assert obs_metrics.latency_quantiles(key)["count"] == 3
    assert obs_metrics.latency_quantiles("no-such-key") is None


# ----------------------------------------------------------------------
# stable metric names
# ----------------------------------------------------------------------
def test_q3_metrics_all_declared(ctx8, rng):
    """Everything a q3 run (and a shuffle) emits into the rollup is
    covered by the documented stable-name table."""
    tracing.reset_trace()
    lf = _q3(ctx8, rng, salt=0.555)
    lf.collect()
    lf.collect()
    undeclared = [
        name for name in tracing.get_trace_report()
        if not obs_metrics.is_declared(name)
    ]
    assert undeclared == [], undeclared


# ----------------------------------------------------------------------
# concurrent isolation (the 8-thread acceptance twin lives in
# tests/test_concurrent_dispatch.py)
# ----------------------------------------------------------------------
@pytest.mark.skipif(
    (os.cpu_count() or 1) < 2,
    reason="two in-flight 8-device collective programs deadlock XLA:CPU's "
           "device-count-sized dispatch pool on a single-core host (the "
           "cross-run rendezvous strand documented in "
           "tests/test_concurrent_dispatch.py) — the hammer twin there "
           "carries the same guard",
)
def test_two_threads_two_disjoint_trees(ctx8, rng, traced):
    lf = _q3(ctx8, rng)
    lf.collect()  # warm: the hammer exercises the lock-free hit path
    obs_export.reset_ring()
    tracing.reset_trace()
    barrier = threading.Barrier(2)

    def worker(_):
        barrier.wait()
        return lf.collect().to_pydict()

    with ThreadPoolExecutor(max_workers=2) as ex:
        a, b = list(ex.map(worker, range(2)))
    assert list(a) == list(b)
    qs = [q for q in obs_export.traces() if q.kind == "plan"]
    assert len(qs) == 2, "two threads must record two disjoint traces"
    assert qs[0].thread != qs[1].thread
    s0 = set(map(id, qs[0].all_spans()))
    s1 = set(map(id, qs[1].all_spans()))
    assert not (s0 & s1), "span trees must not share nodes"
    for q in qs:
        assert any(
            sp.name == "plan.execute" for sp in q.all_spans()
        )
        assert q.counters["plan.cache.hit"][0] == 1
    # the process-global rollup is preserved as the cross-query sum
    assert tracing.get_count("plan.cache.hit") == sum(
        q.counters["plan.cache.hit"][0] for q in qs
    )


# ----------------------------------------------------------------------
# review-hardening regressions
# ----------------------------------------------------------------------
def test_plan_order_unique_ids_on_shared_subplan(ctx8, rng):
    """A reused LazyFrame shares Node objects between branches (a DAG);
    plan_order must keep the first-visit id, never collapse a revisited
    subtree onto a colliding id (which mapped one node's measured span
    onto another node's rendered line)."""
    from cylon_tpu.plan import lower as _lower

    t = ct.Table.from_pydict(
        ctx8,
        {"k": rng.integers(0, 9, 128).astype(np.int32),
         "v": rng.normal(size=128).astype(np.float32)},
    )
    base = t.lazy().filter(col("v") > 0)
    lf = base.union(base)
    ids = list(_lower.plan_order(lf._plan).values())
    assert len(ids) == len(set(ids)), f"colliding node ids: {ids}"
    text = lf.explain(analyze=True)
    assert "== Analyzed plan (executed) ==" in text


def test_pending_records_chain_on_passthrough(ctx8, rng, traced):
    """A plan whose output is a passthrough of a still-deferred table
    (bare Scan root) attaches a second pending record to the SAME table;
    the one count fetch must resolve BOTH queries' traces, not clobber
    the first."""
    t = ct.Table.from_pydict(
        ctx8, {"k": rng.integers(0, 9, 64).astype(np.int32)}
    )
    obs_export.reset_ring()
    d1 = t.lazy().filter(col("k") > 2).dispatch()  # counts deferred
    d2 = d1.lazy().dispatch()  # Scan root: passthrough of d1
    assert d2 is d1
    d1._materialize()
    qs = [q for q in obs_export.traces() if q.kind == "plan"]
    assert len(qs) == 2, [q.name for q in obs_export.traces()]
    assert all(q.device_resolved_s() is not None for q in qs)


# ----------------------------------------------------------------------
# device profiler passthrough
# ----------------------------------------------------------------------
def test_profile_smoke(local_ctx, tmp_path):
    import os

    import jax.numpy as jnp

    d = str(tmp_path / "prof")
    with tracing.profile(d):
        (jnp.arange(128) * 3).block_until_ready()
    produced = [
        os.path.join(r, f) for r, _dirs, fs in os.walk(d) for f in fs
    ]
    assert produced, "jax.profiler must have written a trace"

# ----------------------------------------------------------------------
# critical-path profiler (ISSUE 15): stage clocks, straggler ledger,
# critical-path reports, the measured overlap ledger, fault degradation
# ----------------------------------------------------------------------
@pytest.fixture
def profiled(monkeypatch, traced):
    """Profiler + structured tracing on, re-armed, fresh rollup."""
    from cylon_tpu.obs import prof as obs_prof

    monkeypatch.setenv("CYLON_TPU_PROF", "1")
    obs_prof.reset()
    tracing.reset_trace()
    yield
    obs_prof.reset()


def test_stage_clocks_uniform_vs_one_hot(ctx8, rng, profiled):
    """The straggler ledger separates a one-hot 8-way shuffle (compact /
    relay ratio = world) from a uniform one (ratio ~1); stage-clock
    annotations land on the exchange span."""
    n = 8000
    t = ct.Table.from_pydict(
        ctx8, {"k": rng.integers(0, 2000, n).astype(np.int32)}
    )
    t.shuffle(["k"])
    rep = tracing.report("prof.")
    assert rep["prof.straggler_ratio"]["last"] < 1.5
    assert "prof.stage_ms.pack" in rep
    tracing.reset_trace()
    obs_export.reset_ring()
    hot = ct.Table.from_pydict(ctx8, {"k": np.zeros(n, np.int32)})
    hot.shuffle(["k"])
    rep = tracing.report("prof.")
    assert rep["prof.straggler_ratio"]["last"] > 3.0
    # the measured clocks annotate the owning exchange span
    q = [q for q in obs_export.traces() if q.kind == "op"][-1]
    ex = next(sp for sp in q.all_spans() if sp.name == "shuffle.exchange")
    assert any(k.startswith("prof_") and k.endswith("_ms") for k in ex.attrs)
    assert ex.attrs["prof_straggler"] > 3.0


def test_disabled_profiler_records_nothing(ctx8, rng, traced, monkeypatch):
    monkeypatch.delenv("CYLON_TPU_PROF", raising=False)
    tracing.reset_trace()
    t = ct.Table.from_pydict(
        ctx8, {"k": rng.integers(0, 50, 512).astype(np.int32)}
    )
    t.shuffle(["k"])
    assert not tracing.report("prof.")
    q = [q for q in obs_export.traces() if q.kind == "op"][-1]
    from cylon_tpu.obs import prof as obs_prof

    assert obs_prof.PROF_ATTR not in q.attrs


def test_overlap_gauge_excludes_host_assembly(ctx8, rng, monkeypatch):
    """The measured overlap ledger: the gauge's denominator ends at the
    deferred round-count fetch return, so host-side assembly AFTER the
    fetch (here: an injected delay in the post-fetch ordering stamp)
    cannot drag the efficiency toward zero — the exact bug of the old
    host-wall proxy, which divided by the full assembly wall."""
    import time as _t

    from cylon_tpu.parallel import shuffle as psh

    t = ct.Table.from_pydict(
        ctx8, {"k": rng.integers(0, 40, 1024).astype(np.int32)}
    )
    t.shuffle(["k"])  # warm the kernels: dispatch wall ~ device wall
    real = psh.ordering_after_shuffle
    delay = 0.6

    def slow(kind):
        _t.sleep(delay)  # post-fetch host assembly work
        return real(kind)

    monkeypatch.setattr(psh, "ordering_after_shuffle", slow)
    import cylon_tpu.table as table_mod

    monkeypatch.setattr(table_mod._sh, "ordering_after_shuffle", slow)
    tracing.reset_trace()
    t0 = time.perf_counter()
    t.shuffle(["k"])
    wall = time.perf_counter() - t0
    assert wall >= delay  # the delay really ran inside the shuffle
    eff = tracing.report("shuffle.")["shuffle.overlap_efficiency"]["last"]
    # warm tiny shuffle: issuing overlaps nearly the whole device window.
    # Under the old proxy the injected second lands in the denominator
    # and eff collapses under wall_disp / (wall_disp + 1 s) ~= 0.05.
    assert eff > 0.25, eff
    assert 0.0 <= eff <= 1.0


def test_fused_stage_clocks_resolve_deferred(ctx8, rng, profiled):
    """A fused q3 dispatch attaches window-PENDING stage clocks that
    resolve when the deferred count fetch stamps the query end — and the
    Chrome export then carries per-shard prof.* stage tracks."""
    lf = _q3(ctx8, rng)
    lf.collect()  # compile
    obs_export.reset_ring()
    lf.collect()
    qs = [q for q in obs_export.traces() if q.kind == "plan"]
    assert len(qs) == 1
    from cylon_tpu.obs import prof as obs_prof

    profs = qs[0].attrs.get(obs_prof.PROF_ATTR)
    assert profs, "fused dispatch must attach a stage profile"
    assert all(p.window_s is not None for p in profs), "finalize must run"
    doc = obs_export.chrome_doc()
    assert not obs_export.validate_chrome(doc)
    stage_events = [
        e for e in doc["traceEvents"]
        if e.get("ph") == "X" and str(e["name"]).startswith("prof.")
    ]
    assert len(stage_events) >= ctx8.world_size
    # prof shard tracks must NOT leak into the per-query summary
    tracks = obs_export.summarize(doc)
    assert all(isinstance(tid, int) for tid in tracks)
    # rollup gauges landed at finalize time
    assert "prof.stage_ms.pack" in tracing.report("prof.")


def test_prof_fault_seam_degrades_not_fails(ctx8, rng, monkeypatch):
    """An armed obs.prof seam degrades the profiler to OFF (counted
    prof.degraded) and the query is unaffected."""
    from cylon_tpu import fault
    from cylon_tpu.obs import prof as obs_prof

    monkeypatch.setenv("CYLON_TPU_PROF", "1")
    monkeypatch.setenv("CYLON_TPU_FAULTS", "obs.prof:p=1")
    fault.reset()
    obs_prof.reset()
    c0 = obs_metrics.get_count("prof.degraded")
    try:
        t = ct.Table.from_pydict(
            ctx8, {"k": rng.integers(0, 30, 1024).astype(np.int32)}
        )
        res = t.shuffle(["k"])
        assert res.row_count == 1024  # the query survived
        assert fault.inject.fired("obs.prof") >= 1
        assert obs_metrics.get_count("prof.degraded") == c0 + 1
        assert obs_prof.degraded()
        assert not obs_prof.profiling_active()
    finally:
        monkeypatch.delenv("CYLON_TPU_FAULTS")
        fault.reset()
        obs_prof.reset()


def test_explain_analyze_crit_column(ctx8, rng):
    """explain(analyze=True) prints a critical-path share per node, and
    the shares on the critical path sum to ~100%."""
    import re

    text = _q3(ctx8, rng, salt=0.222).explain(analyze=True)
    shares = [int(m) for m in re.findall(r"crit (\d+)%", text)]
    assert shares, text
    assert 90 <= sum(shares) <= 110  # off-path nodes print crit 0%


def test_traceview_critical_report(ctx8, rng, profiled, tmp_path, capsys):
    """traceview --critical names the bottleneck stage: a skew-side
    stage (relay/collective) on the one-hot shape, a local stage
    (pack/compact) on the uniform shape.

    The uniform leg runs under the codec kill switch: "local stages
    dominate a uniform shuffle" is an XLA-codec stage-algebra claim
    (3-pass pack), and the fused pallas codec exists precisely to shrink
    those stages below the collective — same pin discipline as
    test_lane_pack's bitonic-era gate under CYLON_TPU_NO_RADIX."""
    import tools.traceview as tv
    from cylon_tpu.ops import pallas_codec as _pc

    n = 8000
    out = {}
    for name, keys in (
        ("uniform", rng.integers(0, 2000, n).astype(np.int32)),
        ("one-hot", np.zeros(n, np.int32)),
    ):
        obs_export.reset_ring()
        with _pc.disabled():
            ct.Table.from_pydict(ctx8, {"k": keys}).shuffle(["k"])
        path = str(tmp_path / f"{name}.json")
        obs_export.write_chrome(path)
        assert tv.main([path, "--critical"]) == 0
        out[name] = capsys.readouterr().out
        assert "bottleneck stage:" in out[name]
        assert "measured stage clocks" in out[name]
    assert re_bottleneck(out["one-hot"]) in ("relay", "collective")
    assert re_bottleneck(out["uniform"]) in ("pack", "compact")


def re_bottleneck(text):
    """The bottleneck stage of the MEASURED (stage-clock) track — an
    eager shuffle also records a count-phase op trace whose span-wall
    fold reports 'count'."""
    import re

    m = re.search(r"bottleneck stage: (\w+) \([^)]*measured", text)
    return m.group(1) if m else None


def test_traceview_critical_unprofiled_fallback(ctx8, rng, traced,
                                                tmp_path, capsys):
    """--critical on an UNPROFILED trace falls back to the span-wall
    fold and still reports a path + stage ranking."""
    import tools.traceview as tv

    obs_export.reset_ring()
    ct.Table.from_pydict(
        ctx8, {"k": rng.integers(0, 40, 2048).astype(np.int32)}
    ).shuffle(["k"])
    path = str(tmp_path / "plain.json")
    obs_export.write_chrome(path)
    assert tv.main([path, "--critical"]) == 0
    text = capsys.readouterr().out
    assert "bottleneck stage:" in text
    assert "span-wall fold" in text


def test_traceview_live_renders_ops_endpoint(ctx8, rng, capsys):
    """--live renders a running ops endpoint (healthz + /metrics +
    flight ring) and exits 0; an unreachable endpoint exits 1."""
    import tools.traceview as tv

    srv = obs_export.OpsServer(0)
    port = srv.start()
    try:
        assert tv.main(["--live", f"http://127.0.0.1:{port}"]) == 0
        text = capsys.readouterr().out
        assert "healthz:" in text
    finally:
        srv.stop()
    assert tv.main(["--live", "http://127.0.0.1:9"]) == 1
    assert "unreachable" in capsys.readouterr().err


def test_prof_metrics_all_declared(ctx8, rng, profiled):
    """Everything a profiled one-hot shuffle emits stays covered by the
    stable-name table."""
    tracing.reset_trace()
    ct.Table.from_pydict(ctx8, {"k": np.zeros(4096, np.int32)}).shuffle(["k"])
    undeclared = [
        name for name in tracing.get_trace_report()
        if not obs_metrics.is_declared(name)
    ]
    assert not undeclared, undeclared
