"""Task-based all-to-all (reference experimental ArrowTaskAllToAll /
LogicalTaskPlan, cpp/src/cylon/arrow/arrow_task_all_to_all.{h,cpp}):
over-decomposition into T logical tasks routed to P workers.
"""
import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.parallel import LogicalTaskPlan


@pytest.fixture
def tbl(world_ctx, rng):
    df = pd.DataFrame(
        {
            "k": rng.integers(0, 100, 300).astype(np.int64),
            "v": rng.normal(size=300),
        }
    )
    return df, ct.Table.from_pandas(world_ctx, df)


def test_plan_round_robin(ctx8):
    plan = LogicalTaskPlan(10, ctx8.world_size)
    assert plan.n_tasks == 10
    assert plan.worker_of(0) == 0 and plan.worker_of(9) == 9 % 8
    assert set(plan.tasks_of(0).tolist()) == {0, 8}


def test_plan_explicit_map_validation(ctx8):
    plan = LogicalTaskPlan({0: 3, 1: 0, 2: 3}, ctx8.world_size)
    assert plan.worker_of(2) == 3
    with pytest.raises(ValueError):
        LogicalTaskPlan({0: 99}, ctx8.world_size)  # worker out of range
    with pytest.raises(ValueError):
        LogicalTaskPlan({1: 0}, ctx8.world_size)  # non-dense task ids


def test_task_partition_content_and_placement(tbl):
    df, t = tbl
    world = t.world_size
    n_tasks = 3 * world  # over-decomposition: T > P
    plan = LogicalTaskPlan(n_tasks, world)
    parts = t.task_partition(["k"], plan)
    assert set(parts.keys()) == set(range(n_tasks))
    # content: the union of all task tables is exactly the input (multiset)
    total = sum(p.row_count for p in parts.values())
    assert total == len(df)
    all_rows = pd.concat([p.to_pandas() for p in parts.values() if p.row_count])
    assert sorted(all_rows["k"].tolist()) == sorted(df["k"].tolist())
    assert np.isclose(all_rows["v"].sum(), df["v"].sum())
    for t_id, p in parts.items():
        assert p.column_names == ["k", "v"]  # __task__ dropped
        # placement: every row of task t lives on worker plan.worker_of(t)
        owner = plan.worker_of(t_id)
        counts = p.row_counts
        for w in range(world):
            if w != owner:
                assert counts[w] == 0, (t_id, owner, counts)


def test_task_determinism_same_key_same_task(tbl):
    df, t = tbl
    plan = LogicalTaskPlan(5, t.world_size)
    parts = t.task_partition(["k"], plan)
    # each distinct key appears in exactly one task
    seen = {}
    for t_id, p in parts.items():
        for k in p.to_pandas()["k"].unique():
            assert k not in seen, f"key {k} split across tasks {seen[k]},{t_id}"
            seen[k] = t_id
