"""Chunked, compute-overlapped shuffle engine (ISSUE 2).

Pins the tentpole's observable contracts:

- collective count: the count exchange rides the payload collective's
  header lanes, so an eager distributed join issues EXACTLY 2 traced
  collectives (one per side's shuffle). The pre-fusion engine issued 4
  (2 count all_to_alls + 2 payload all_to_alls) — that pinned baseline
  flipped with the fusion and this test is its regression gate.
- the fused pipeline halves its shuffle collectives the same way
  (one all_to_all per respill round per side, plus the two overflow psums).
- the byte budget drives round count K and peak per-round exchange bytes,
  and chunked output is differential-equal to the unchunked shuffle.
- tracing carries the per-round pack/collective/compact spans and the
  overlap-efficiency gauge.
"""
import numpy as np
import pytest

import cylon_tpu as ct
from cylon_tpu.analysis import contracts
from cylon_tpu.engine import round_cap
from cylon_tpu.parallel import shuffle as _sh


def _traced_collectives(op):
    """(total traced collective count, per-program collective bytes) for one
    warm call of ``op`` — the BENCH.md accounting (benchmarks/roofline)."""
    from benchmarks.roofline import traced_collectives

    return traced_collectives(op, warm=True)


def _ctx8(devices):
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:8]))


def test_distributed_join_exactly_two_collectives(devices, rng):
    """The acceptance gate: traced collectives per eager distributed join
    dropped from 4 (pre-fusion pinned baseline) to 2. Pinned with the
    semi-join sketch filter off — the filter, when it engages, adds ONE
    sketch all_gather on top of the two payload all_to_alls (that 2+1
    shape is pinned by tests/test_semi_filter.py)."""
    from cylon_tpu.ops import sketch as _sk

    ctx = _ctx8(devices)
    lt = ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(0, 200, 2000).astype(np.int32),
         "v": rng.normal(size=2000).astype(np.float32)},
    )
    rt = ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(0, 200, 1500).astype(np.int32),
         "w": rng.normal(size=1500).astype(np.float32)},
    )
    with _sk.disabled():
        colls, _ = _traced_collectives(
            lambda: lt.distributed_join(rt, on="k", how="inner")
        )
    # the pinned number lives in the contract table (analysis/contracts.py)
    # — graft-lint checks the same constant against the plan registry
    assert colls == contracts.DIST_JOIN_PAYLOAD_COLLECTIVES, (
        f"expected {contracts.DIST_JOIN_PAYLOAD_COLLECTIVES} collectives "
        f"per distributed join, traced {colls}"
    )


def test_single_shuffle_one_collective_per_round(devices, rng):
    """A K-round chunked shuffle issues exactly K collectives — the count
    exchange adds none."""
    from cylon_tpu.utils.tracing import report, reset_trace

    ctx = _ctx8(devices)
    t = ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(0, 100, 4000).astype(np.int32),
         "v": rng.normal(size=4000).astype(np.float32)},
    )
    for budget in (1 << 40, 8 * 64 * 12):
        reset_trace()
        t.shuffle(["k"], byte_budget=budget)
        rounds = int(report("shuffle.")["shuffle.rounds"]["rows"])
        colls, _ = _traced_collectives(
            lambda: t.shuffle(["k"], byte_budget=budget)
        )
        assert colls == contracts.shuffle_collectives(rounds), (
            budget, rounds, colls,
        )


def test_fused_pipeline_collectives_halved(devices):
    """The fused join program's shuffle rounds use the header-fused exchange:
    2 sides x (1 + respill) all_to_alls + the 2 overflow psums — the
    pre-fusion program traced twice the all_to_alls."""
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from benchmarks.roofline import analyze
    from cylon_tpu.ops import join as _j
    from cylon_tpu.parallel.pipeline import make_distributed_join_step

    world, cap = 4, 64
    mesh = Mesh(np.array(devices[:world]), ("dp",))
    for respill in (0, 1, 2):
        step = make_distributed_join_step(
            mesh, "dp", l_key_idx=(0,), r_key_idx=(0,), how=_j.INNER,
            bucket_cap=32, join_cap=512, respill=respill,
        )
        import jax

        sds = jax.ShapeDtypeStruct
        cols = [(sds((world * cap,), jnp.int32), None),
                (sds((world * cap,), jnp.float32), None)]
        counts = sds((world,), jnp.int32)
        rep = analyze(step, (cols, counts, cols, counts), ())
        expect = contracts.fused_join_collectives(respill)
        assert rep.collective_count == expect, (
            respill, rep.collective_count, expect
        )


def test_budget_bounds_peak_round_bytes(devices, rng):
    """Peak traced bytes of any single collective program stay within the
    byte budget (+ the header rows), while TOTAL shuffled volume is
    unchanged across K — chunking caps memory, not traffic."""
    ctx = _ctx8(devices)
    n = 4096
    t = ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(0, 1000, n).astype(np.int32),
         "v": rng.normal(size=n).astype(np.float32)},
    )
    row_bytes = _sh.exchange_row_bytes(t._flat_cols())
    world = t.world_size
    totals = []
    for cap_target in (256, 64, 16):
        budget = world * cap_target * row_bytes
        colls, per_bytes = _traced_collectives(
            lambda: t.shuffle(["k"], byte_budget=budget)
        )
        header = world * _sh.HEADER_ROWS * row_bytes
        assert max(per_bytes) <= budget + header, (cap_target, per_bytes)
        totals.append(sum(per_bytes))
    # total volume constant-ish across K: only per-round header rows differ
    assert max(totals) - min(totals) <= 64 * world * row_bytes


def test_chunked_output_matches_unchunked(devices, rng):
    """Differential: tiny-budget many-round shuffle == huge-budget shuffle
    (as a row multiset), with identical destination shards per row."""
    ctx = _ctx8(devices)
    n = 3000
    t = ct.Table.from_pydict(
        ctx,
        {"k": rng.integers(-50, 50, n).astype(np.int32),
         "v": rng.normal(size=n).astype(np.float32)},
    )
    base = t.shuffle(["k"], byte_budget=1 << 40)
    for budget in (8 * 16 * 12, 8 * 64 * 12):
        got = t.shuffle(["k"], byte_budget=budget)
        # routing is budget-independent: same rows land on the same shards
        assert (got.row_counts == base.row_counts).all()
        gp = got.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
        bp = base.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
        assert np.array_equal(gp["k"].to_numpy(), bp["k"].to_numpy())
        assert np.allclose(gp["v"].to_numpy(), bp["v"].to_numpy())


def test_round_spans_and_overlap_gauge(devices, rng):
    """tracing.report() carries the per-round phase spans and the
    overlap-efficiency gauge (a 0..1 ratio)."""
    from cylon_tpu.utils.tracing import report, reset_trace

    ctx = _ctx8(devices)
    t = ct.Table.from_pydict(
        ctx, {"k": rng.integers(0, 30, 512).astype(np.int32)}
    )
    reset_trace()
    t.shuffle(["k"])
    rep = report("shuffle.")
    rounds = int(rep["shuffle.rounds"]["rows"])
    for phase in ("pack", "collective", "compact"):
        assert rep[f"shuffle.round.{phase}"]["count"] == rounds
    eff = rep["shuffle.overlap_efficiency"]
    assert eff["count"] == 1
    assert 0.0 <= eff["total_s"] <= 1.0


def test_pure_f64_passthrough_shuffle(devices, rng):
    """A table with NO int32 lanes (pure f64, no validity) takes the
    dedicated-count-lane fallback and still round-trips correctly."""
    import jax

    if not jax.config.jax_enable_x64:
        pytest.skip("x64 disabled")
    ctx = _ctx8(devices)
    n = 1000
    k = rng.integers(0, 40, n).astype(np.float64)
    t = ct.Table.from_pydict(ctx, {"k": k})
    s = t.shuffle(["k"])
    assert s.row_count == n
    got = np.sort(s.to_pandas()["k"].to_numpy())
    assert np.allclose(got, np.sort(k))
