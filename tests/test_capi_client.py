"""Foreign-language consumer of the C ABI: a standalone C program drives
read_csv -> distributed_join -> distributed_sort -> project -> write_csv in
its OWN process through dlopen + the embedded interpreter.

Reference analog: the JVM client Table.java
(java/src/main/java/org/cylondata/cylon/Table.java:63-238) driving the C++
core over JNI. The in-process ctypes round-trip lives in
test_native_runtime.py; this test exercises the Py_InitializeEx path a real
FFI consumer hits.
"""
import os
import subprocess
import sys
import sysconfig

import numpy as np
import pandas as pd
import pytest

from cylon_tpu import native

_CLIENT_SRC = os.path.join(
    os.path.dirname(native.__file__), "examples", "capi_client.c"
)


def _build_client(tmp_path) -> str:
    exe = str(tmp_path / "capi_client")
    r = subprocess.run(
        ["gcc", "-O2", _CLIENT_SRC, "-o", exe, "-ldl"],
        capture_output=True,
        text=True,
        timeout=120,
    )
    if r.returncode != 0:
        pytest.skip(f"client build failed: {r.stderr[-300:]}")
    return exe


def test_c_client_end_to_end(tmp_path):
    so = native.build_capi()
    if so is None:
        pytest.skip("capi build failed (no libpython?)")
    exe = _build_client(tmp_path)

    rng = np.random.default_rng(5)
    l = pd.DataFrame(
        {"k": rng.integers(0, 20, 200), "x": rng.normal(size=200)}
    )
    r = pd.DataFrame(
        {"k": rng.integers(0, 20, 150), "y": rng.normal(size=150)}
    )
    lp, rp = str(tmp_path / "l.csv"), str(tmp_path / "r.csv")
    out = str(tmp_path / "out.csv")
    l.to_csv(lp, index=False)
    r.to_csv(rp, index=False)

    env = dict(os.environ)
    # the embedded interpreter must see the repo package and run on the
    # virtual CPU mesh (CYLON_TPU_PLATFORM uses the jax.config route — the
    # JAX_PLATFORMS env var provably hangs on tunneled-TPU images)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        [repo] + [p for p in sys.path if p and p != repo]
    )
    env["CYLON_TPU_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    )
    env.pop("JAX_PLATFORMS", None)
    # dynamic linker must find libpython for the capi .so
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    env["LD_LIBRARY_PATH"] = os.pathsep.join(
        filter(None, [libdir, env.get("LD_LIBRARY_PATH", "")])
    )

    res = subprocess.run(
        [exe, so, lp, rp, out],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
    )
    assert res.returncode == 0, f"stdout={res.stdout}\nstderr={res.stderr[-2000:]}"
    exp = l.merge(r, on="k")
    assert f"rows={len(exp)}" in res.stdout, res.stdout
    assert "cols=3" in res.stdout, res.stdout

    got = pd.read_csv(out)
    assert list(got.columns) == ["k_x", "x", "y"]
    assert len(got) == len(exp)
    assert (np.diff(got["k_x"].to_numpy()) >= 0).all()  # distributed_sort order
    assert np.isclose(got["x"].sum(), exp["x"].sum())
