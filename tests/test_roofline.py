"""benchmarks/roofline.py analyzer sanity: primitive counting and traffic
math on known-shape programs (the model feeds BENCH_TPU.md's %membw column,
so its bookkeeping needs a regression net)."""
import os
import sys

import jax
import jax.numpy as jnp
from cylon_tpu.compat import shard_map
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.roofline import (
    GATHER_PASS_EQ,
    _bitonic_passes,
    analyze,
    model_seconds,
)


def test_counts_one_sort_with_pass_weighting():
    n = 1 << 12

    def f(x, p):
        return jax.lax.sort((x, p), num_keys=1, is_stable=True)

    rep = analyze(
        f,
        jax.ShapeDtypeStruct((n,), jnp.int32),
        jax.ShapeDtypeStruct((n,), jnp.int32),
    )
    assert rep.sort_count == 1
    assert rep.sort_bytes_per_pass == 2 * n * 4
    assert rep.sort_pass_bytes == 2 * n * 4 * _bitonic_passes(n)


def test_counts_gather_pass_equivalents():
    n = 1 << 10

    def f(x, idx):
        return x[idx]

    rep = analyze(
        f,
        jax.ShapeDtypeStruct((n,), jnp.float32),
        jax.ShapeDtypeStruct((n,), jnp.int32),
    )
    assert rep.sort_count == 0
    assert rep.gather_bytes > 0
    # weighted: in+out bytes x pass-equivalents
    assert rep.gather_bytes == pytest.approx(3 * n * 4 * GATHER_PASS_EQ)


def test_recurses_into_jit_and_shard_map(devices):
    from jax.sharding import Mesh, PartitionSpec

    mesh = Mesh(np.array(devices[:2]), ("dp",))
    n = 256

    def kern(x):
        s, = jax.lax.sort((x,), num_keys=1)
        return s

    f = jax.jit(
        shard_map(
            kern, mesh=mesh,
            in_specs=PartitionSpec("dp"), out_specs=PartitionSpec("dp"),
        )
    )
    rep = analyze(f, jax.ShapeDtypeStruct((2 * n,), jnp.int32))
    assert rep.sort_count == 1  # found through jit -> shard_map nesting


def test_engine_kernel_recording(ctx8, rng):
    """engine.record_kernels captures every get_kernel dispatch (fn, args)
    so eager op chains can be roofline-modeled; disabled leaves dispatch
    untouched."""
    import cylon_tpu as ct
    from cylon_tpu import engine

    t = ct.Table.from_pydict(
        ctx8, {"k": rng.integers(0, 9, 64).astype(np.int32)}
    )
    engine.record_kernels(True)
    try:
        t.unique()
    finally:
        ks = engine.recorded_kernels()
        engine.record_kernels(False)
    assert len(ks) >= 1
    fn, args = ks[0]
    from benchmarks.roofline import analyze

    rep = analyze(fn, *args)
    assert rep.sort_count >= 1  # unique is sort-based

    engine.record_kernels(False)
    assert engine.recorded_kernels() == []


def test_model_seconds_scales_with_bandwidth():
    def f(x, p):
        return jax.lax.sort((x, p), num_keys=1)

    rep = analyze(
        f,
        jax.ShapeDtypeStruct((1 << 16,), jnp.int32),
        jax.ShapeDtypeStruct((1 << 16,), jnp.int32),
    )
    assert model_seconds(rep, 100.0) == pytest.approx(
        2 * model_seconds(rep, 200.0)
    )


def test_pallas_call_priced_streamed_not_recursed():
    """The model must price a pallas_call as one read + one write of its
    operands and must NOT walk the kernel body (whose in-VMEM jnp.take
    would otherwise be priced at the HBM per-element gather rate,
    overstating kernel traffic ~400x)."""
    from cylon_tpu.ops.pallas_gather import expand_available, expand_rows

    if not expand_available():
        pytest.skip("pallas unavailable")
    import jax.numpy as jnp

    m = 4000
    src = jnp.asarray(np.arange(4 * m, dtype=np.int32).reshape(4, m))
    li = jnp.asarray(np.repeat(np.arange(m), 2).astype(np.int32))
    rep = analyze(
        lambda s, l: expand_rows(s, l, impl="take", interpret=False), src, li
    )
    assert rep.gather_bytes == 0, rep.by_prim
    assert "pallas_call" in rep.by_prim
    # streamed pricing: same order as operand+output bytes, nowhere near
    # the ~400x per-element-gather figure
    raw = (4 * m + len(li) + 4 * len(li)) * 4
    assert rep.by_prim["pallas_call"] < 3 * raw


def test_container_prims_not_double_counted():
    """pjit/shard_map containers recurse but must not add their own in/out
    bytes on top of their bodies'."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return x * 2.0

    x = jnp.zeros((1024,), jnp.float32)
    rep = analyze(f, x)
    # one multiply: ~in+out = 8KB; a double-counted pjit boundary would
    # add another ~8KB on top
    assert rep.elementwise_bytes <= 3 * 8192, rep.elementwise_bytes


def test_scan_body_scaled_by_trip_count():
    """A scan body executes `length` times — its sorts/collectives must be
    scaled, not counted once (the K-sliced fused join runs K rounds in ONE
    scan; an unscaled walk under-reported its collective volume by K)."""
    import jax
    import jax.numpy as jnp

    K = 7

    @jax.jit
    def f(x):
        def body(carry, _):
            s = jax.lax.sort(carry)
            return s, jnp.sum(s)

        out, sums = jax.lax.scan(body, x, None, length=K)
        return out, sums

    x = jnp.zeros((2048,), jnp.int32)
    rep = analyze(f, x)
    assert rep.sort_count == K, rep.sort_count
    # pass-weighted bytes scale with K too
    one = analyze(jax.jit(lambda x: jax.lax.sort(x)), x)
    assert abs(rep.sort_pass_bytes - K * one.sort_pass_bytes) < 1e-6
