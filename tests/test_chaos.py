"""Chaos-certified execution (ISSUE 14): the fault-injection registry,
the typed error taxonomy, and every degradation mechanism the seams
exercise — plus the satellite regressions (close() leak, stale spill-dir
reclamation, admission-lease release on error paths, shed-reason
counters) that previously had no coverage.

The invariant under test everywhere: a failure ends in exactly one of
{oracle-identical result, typed CylonError} with every admission lease
and spill arena released — never a stranded future, never a leaked
byte, never a dead process."""
import gc
import os
import subprocess
import threading
import time

import numpy as np
import pytest

import cylon_tpu as ct
from cylon_tpu import col, fault
from cylon_tpu.fault import inject as finject
from cylon_tpu.fault.errors import (
    CylonError,
    QueryExecError,
    QueryTimeoutError,
    SchedulerClosedError,
    SpillIOError,
    WorkerDiedError,
)
from cylon_tpu.parallel import spill as spill_mod
import importlib

from cylon_tpu.serve import ServeOverloadError, ServeScheduler, Unbatchable

# the submodule, not the serve.scheduler() factory that shadows it
sched_mod = importlib.import_module("cylon_tpu.serve.scheduler")
from cylon_tpu.utils import tracing


@pytest.fixture(scope="module")
def cctx(devices):
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:4]))


@pytest.fixture(autouse=True)
def _disarm(monkeypatch):
    """Every test starts and ends fault-free (the module-level no-op)."""
    monkeypatch.delenv("CYLON_TPU_FAULTS", raising=False)
    fault.reset()
    yield
    monkeypatch.delenv("CYLON_TPU_FAULTS", raising=False)
    fault.reset()


def _arm(monkeypatch, spec):
    monkeypatch.setenv("CYLON_TPU_FAULTS", spec)
    fault.reset()


def _mk_binding(ctx, rng, n, key_lo=0, key_hi=20):
    ta = ct.Table.from_pydict(ctx, {
        "k": rng.integers(key_lo, key_hi, n).astype(np.int32),
        "v": rng.integers(-50, 50, n).astype(np.float32),
    })
    tb = ct.Table.from_pydict(ctx, {
        "rk": rng.integers(key_lo, key_hi, n).astype(np.int32),
        "w": rng.integers(-50, 50, n).astype(np.float32),
    })
    return ta, tb


def _q3(ta, tb, lit=0.0):
    return (
        ta.lazy()
        .join(tb.lazy(), left_on="k", right_on="rk")
        .filter(col("w") > lit)
        .groupby("k", {"v": "sum"})
    )


def _canon(t):
    d = t.to_pydict()
    cols = sorted(d)
    return cols, sorted(zip(*(d[c] for c in cols)))


# ----------------------------------------------------------------------
# the registry: grammar, determinism, no-op discipline
# ----------------------------------------------------------------------
def test_spec_grammar_and_errors():
    specs = fault.parse_spec(
        "serve.single_exec:p=0.25:kind=exec:n=3:seed=9:match=abc, "
        "serve.worker"
    )
    sw = specs["serve.single_exec"]
    assert (sw.p, sw.kind, sw.n, sw.seed, sw.match) == (
        0.25, "exec", 3, 9, "abc")
    assert specs["serve.worker"].kind == "die"  # per-seam default
    assert specs["serve.worker"].p == 1.0
    for bad in (
        "not.a.seam",                      # unknown seam
        "spill.write:p=2",                 # p out of range
        "spill.write:kind=EXPLODE",        # unknown kind
        "spill.write:zap=1",               # unknown field
        "spill.write:n=banana",            # unparseable value
        "obs.journal:kind=exec",           # typed kind on an I/O seam:
        "spill.read:kind=die",             # would escape the OSError
                                           # degradation ladders
        "spill.write:match=abc",           # match on a keyless seam can
                                           # never fire: armed-but-inert
    ):
        with pytest.raises(fault.FaultSpecError):
            fault.parse_spec(bad)
    fault.parse_spec("serve.batch_exec:kind=ENOSPC")  # errno on serve: ok


def test_deterministic_replay(monkeypatch):
    """Same (seed, seam, call sequence) => identical injection pattern —
    the replayability the chaos campaign rests on."""

    def pattern():
        _arm(monkeypatch, "obs.journal:p=0.4:seed=11")
        out = []
        for _ in range(40):
            try:
                finject.check("obs.journal")
                out.append(0)
            except OSError:
                out.append(1)
        return out

    first = pattern()
    assert sum(first) > 0 and sum(first) < 40
    assert pattern() == first
    _arm(monkeypatch, "obs.journal:p=0.4:seed=12")
    diff = []
    for _ in range(40):
        try:
            finject.check("obs.journal")
            diff.append(0)
        except OSError:
            diff.append(1)
    assert diff != first  # a different seed is a different campaign


def test_disabled_is_module_level_noop(monkeypatch):
    assert finject.check is finject._check_noop
    _arm(monkeypatch, "spill.write")
    assert finject.check is finject._check_armed
    monkeypatch.delenv("CYLON_TPU_FAULTS")
    fault.reset()
    assert finject.check is finject._check_noop
    finject.check("spill.write")  # and it really does nothing


def test_cap_match_and_kinds(monkeypatch):
    import errno

    _arm(monkeypatch, "spill.write:n=2")
    fired = 0
    for _ in range(10):
        try:
            finject.check("spill.write")
        except OSError as e:
            assert e.errno == errno.ENOSPC  # seam default kind
            fired += 1
    assert fired == 2 and fault.fired("spill.write") == 2
    # match= poisons only the targeted key
    _arm(monkeypatch, "serve.single_exec:match=bad")
    finject.check("serve.single_exec", key="good-binding")
    with pytest.raises(QueryExecError):
        finject.check("serve.single_exec", key="the-bad-one")
    # digit-bounded: a match ending in digits never splits a longer
    # admission seq — #q2 must not also poison #q20..#q29
    _arm(monkeypatch, "serve.single_exec:match=#q2")
    finject.check("serve.single_exec", key="Join#q20")
    finject.check("serve.single_exec", key="Join#q21 Join#q23 Join#q29")
    with pytest.raises(QueryExecError):
        finject.check("serve.single_exec", key="Join#q2")
    with pytest.raises(QueryExecError):
        finject.check("serve.single_exec", key="Join#q1 Join#q2 Join#q3")
    # kind families map to the typed taxonomy
    _arm(monkeypatch, "serve.worker:kind=die")
    with pytest.raises(WorkerDiedError):
        finject.check("serve.worker")
    _arm(monkeypatch, "serve.single_exec:kind=timeout")
    with pytest.raises(QueryTimeoutError):
        finject.check("serve.single_exec")


def test_typoed_seam_site_fails_loudly(monkeypatch):
    """A check() site naming an unknown seam is silently dead while
    disarmed (free), but any armed campaign flags it immediately."""
    finject.check("spil.write")  # disarmed: the no-op swallows anything
    _arm(monkeypatch, "obs.journal:p=0")
    with pytest.raises(fault.FaultSpecError):
        finject.check("spil.write")


def test_seam_hook_sync_budgets_are_live():
    """The contracts pin on the seam hooks must resolve to REAL
    functions — a zero-owner budget is silently skipped by the lint
    pass, which would make the 'seams can never sync' guarantee dead."""
    from cylon_tpu.analysis import contracts

    for suffix in ("inject._check_armed", "inject._check_noop"):
        assert suffix in contracts.SYNC_SITE_BUDGETS
        assert contracts.SYNC_SITE_BUDGETS[suffix].sites == 0
    assert callable(finject._check_armed)
    assert callable(finject._check_noop)


def test_error_taxonomy():
    """The scope/retryable axes + the compatibility re-parenting."""
    assert issubclass(ServeOverloadError, CylonError)
    assert issubclass(ServeOverloadError, RuntimeError)  # legacy catch
    assert issubclass(Unbatchable, CylonError)
    assert issubclass(SpillIOError, OSError)
    assert issubclass(QueryTimeoutError, TimeoutError)
    assert issubclass(SchedulerClosedError, RuntimeError)
    assert ct.CylonError is CylonError  # exported at the package root
    e = QueryExecError("boom", fingerprint="fp", binding="b3")
    assert e.scope == "query" and not e.retryable and e.binding == "b3"
    assert SpillIOError().retryable and WorkerDiedError().retryable
    assert SchedulerClosedError().scope == "context"


# ----------------------------------------------------------------------
# batched serving: poisoned-binding isolation + quarantine (the
# acceptance pin)
# ----------------------------------------------------------------------
def test_poisoned_binding_isolation_b8(cctx, rng, monkeypatch):
    """ONE poisoned binding in a B=8 stacked group fails exactly one
    future (typed QueryExecError), the other 7 return the serial
    oracle's exact rows via the single fallback, and serve.batch_fallback
    counts the event."""
    monkeypatch.setenv("CYLON_TPU_SERVE_BATCH_MAX", "8")
    plans = [
        _q3(*_mk_binding(cctx, rng, 120 + 11 * i), lit=0.061)
        for i in range(8)
    ]
    oracle = [_canon(p.collect()) for p in plans]
    fb0 = tracing.get_count("serve.batch_fallback")
    _arm(monkeypatch,
         "serve.batch_exec:p=1:n=1,serve.single_exec:p=1:n=1")
    s = ServeScheduler(cctx, auto_start=False)
    futs = [s.submit(p) for p in plans]
    s.run_pending()
    errs, good = [], []
    for i, f in enumerate(futs):
        e = f.exception(timeout=60)
        if e is not None:
            errs.append((i, e))
        else:
            good.append((i, _canon(f.result(timeout=60))))
    assert len(errs) == 1, f"want exactly 1 poisoned future, got {errs}"
    assert isinstance(errs[0][1], QueryExecError)
    assert len(good) == 7
    for i, c in good:
        assert c == oracle[i], f"binding {i} diverged in the fallback"
    assert tracing.get_count("serve.batch_fallback") == fb0 + 1
    assert s.stats()["leases"] == 0  # every lease released or consumed
    assert s.stats()["inflight_bytes"] == 0


def test_match_campaign_targets_one_binding_e2e(cctx, rng, monkeypatch):
    """The documented `match=` campaign is expressible END TO END: the
    serve seam keys are per-binding (`<PlanRoot>#q<admission-seq>`), so
    arming both serve seams with `match=#q3` — no n= cap — fails exactly
    the fourth admitted binding through batch formation AND the single
    fallback, and every other binding returns the serial oracle."""
    monkeypatch.setenv("CYLON_TPU_SERVE_BATCH_MAX", "8")
    plans = [
        _q3(*_mk_binding(cctx, rng, 100 + 9 * i), lit=0.0413)
        for i in range(8)
    ]
    oracle = [_canon(p.collect()) for p in plans]
    _arm(monkeypatch,
         "serve.batch_exec:match=#q3,serve.single_exec:match=#q3")
    s = ServeScheduler(cctx, auto_start=False)
    futs = [s.submit(p) for p in plans]
    s.run_pending()
    for i, f in enumerate(futs):
        e = f.exception(timeout=60)
        if i == 3:
            assert isinstance(e, QueryExecError), e
            assert "#q3" in (e.binding or ""), e.binding
        else:
            assert e is None, f"binding {i} unexpectedly failed: {e}"
            assert _canon(f.result(timeout=60)) == oracle[i]
    assert s.stats()["leases"] == 0
    assert s.stats()["inflight_bytes"] == 0


def test_batch_quarantine_cooldown(cctx, rng, monkeypatch):
    """After a stacked-batch failure the fingerprint's groups form as
    singles (no new batch) until the cooldown lapses, then batching
    resumes."""
    monkeypatch.setenv("CYLON_TPU_SERVE_BATCH_MAX", "8")
    plans = lambda lit: [  # noqa: E731
        _q3(*_mk_binding(cctx, rng, 90 + 7 * i), lit=lit) for i in range(3)
    ]
    wave = plans(0.0721)
    _arm(monkeypatch, "serve.batch_exec:p=1:n=1")
    s = ServeScheduler(cctx, auto_start=False)
    futs = [s.submit(p) for p in wave]
    s.run_pending()
    assert all(f.exception(timeout=30) is None for f in futs)
    monkeypatch.delenv("CYLON_TPU_FAULTS")
    fault.reset()
    # quarantined: the next wave of the SAME fingerprint runs as singles
    q0 = tracing.get_count("serve.batch_quarantined")
    b0 = tracing.get_count("serve.batches")
    futs = [s.submit(p) for p in plans(0.0721)]
    s.run_pending()
    [f.result(timeout=30) for f in futs]
    assert tracing.get_count("serve.batch_quarantined") > q0
    assert tracing.get_count("serve.batches") == b0
    # cooldown lapses (forced, so the test never races real compile
    # walls against a second-scale sleep): batching resumes
    with s._lock:
        for k in list(s._quarantine):
            s._quarantine[k] = time.monotonic() - 1.0
    futs = [s.submit(p) for p in plans(0.0721)]
    s.run_pending()
    [f.result(timeout=30) for f in futs]
    assert tracing.get_count("serve.batches") == b0 + 1


# ----------------------------------------------------------------------
# worker supervision + deadlines
# ----------------------------------------------------------------------
def test_worker_death_supervision_and_respawn(cctx, rng, monkeypatch):
    plans = [
        _q3(*_mk_binding(cctx, rng, 80 + 9 * i), lit=0.083)
        for i in range(3)
    ]
    oracle = [_canon(p.collect()) for p in plans]
    _arm(monkeypatch, "serve.worker:n=1")
    r0 = tracing.get_count("serve.worker_respawn")
    s = ServeScheduler(cctx, auto_start=True)
    s.pause()
    futs = [s.submit(p) for p in plans]
    # a record of a DIFFERENT fingerprint rides behind the doomed group:
    # the dying worker must respawn the drain itself — this caller only
    # waits on the future (no further submit / drain to trigger one)
    other = _q3(*_mk_binding(cctx, rng, 75, key_hi=11), lit=0.089)
    other_oracle = _canon(other.collect())
    tail = s.submit(other)
    s.resume()
    for f in futs:
        assert isinstance(f.exception(timeout=30), WorkerDiedError)
    assert _canon(tail.result(timeout=60)) == other_oracle
    assert s.stats()["leases"] == 0  # the dying worker released them
    # the next wave respawns the worker and serves correctly
    futs = [s.submit(p) for p in plans]
    assert s.drain(timeout=60)
    for i, f in enumerate(futs):
        assert _canon(f.result(timeout=60)) == oracle[i]
    assert tracing.get_count("serve.worker_respawn") > r0
    s.close()


def test_worker_respawn_noprogress_bounded(cctx, rng, monkeypatch):
    """A deterministic PRE-TAKE worker failure (no group taken, so no
    queue progress even typed) must not respawn-loop forever:
    supervision gives up after RESPAWN_NOPROGRESS_MAX consecutive
    no-progress deaths and fails the queue typed instead."""

    def boom(self):
        raise MemoryError("pre-take failure")

    monkeypatch.setattr(ServeScheduler, "_take_group_locked", boom)
    r0 = tracing.get_count("serve.worker_respawn")
    s = ServeScheduler(cctx, auto_start=True)
    fut = s.submit(_q3(*_mk_binding(cctx, rng, 60), lit=0.021))
    assert isinstance(fut.exception(timeout=30), WorkerDiedError)
    assert s.stats()["leases"] == 0 and s.stats()["inflight_bytes"] == 0
    burst = tracing.get_count("serve.worker_respawn") - r0
    assert burst <= sched_mod.RESPAWN_NOPROGRESS_MAX
    s.close()


@pytest.mark.slow  # e2e thread hammer; CI chaos-smoke drives this path
def test_blocked_submitters_survive_worker_death(cctx, rng, monkeypatch):
    """Liveness: submitters parked on backpressure when the worker dies
    must resurrect the drain themselves — every query resolves (typed or
    identical), nothing hangs, every lease comes home."""
    from concurrent.futures import ThreadPoolExecutor

    bindings = [_mk_binding(cctx, rng, 300, key_hi=23) for _ in range(8)]
    plans = [_q3(ta, tb, lit=0.041) for ta, tb in bindings]
    est = ct.serve.estimate_query_bytes(list(bindings[0]))
    monkeypatch.setenv("CYLON_TPU_SERVE_INFLIGHT_BYTES", str(3 * est))
    _arm(monkeypatch, "serve.worker:n=1")
    s = ServeScheduler(cctx, auto_start=True)

    def one(p):
        while True:
            try:
                fut = s.submit(p)
                break
            except ServeOverloadError:
                time.sleep(0.005)
        try:
            fut.result(timeout=60)
            return "ok"
        except CylonError:
            return "typed"

    with ThreadPoolExecutor(max_workers=8) as ex:
        outcomes = list(ex.map(one, plans))
    assert all(o in ("ok", "typed") for o in outcomes)
    assert any(o == "ok" for o in outcomes)  # the respawned worker served
    assert s.drain(timeout=30)
    assert s.stats()["leases"] == 0 and s.stats()["inflight_bytes"] == 0
    s.close()


def test_deadline_fails_typed_instead_of_hanging(cctx, rng, monkeypatch):
    monkeypatch.setenv("CYLON_TPU_SERVE_DEADLINE_MS", "60")
    s = ServeScheduler(cctx, auto_start=False)  # nobody will ever drain
    fut = s.submit(_q3(*_mk_binding(cctx, rng, 70), lit=0.0917))
    e0 = tracing.get_count("serve.errors")
    t0 = time.monotonic()
    with pytest.raises(QueryTimeoutError):
        fut.result(timeout=30)
    assert time.monotonic() - t0 < 5  # failed at the deadline, no hang
    assert isinstance(fut.exception(timeout=1), QueryTimeoutError)
    # caller-side deadline failures feed the SLO errors rule too
    assert tracing.get_count("serve.errors") == e0 + 1
    assert s.stats()["leases"] == 0 and s.stats()["inflight_bytes"] == 0
    # scheduler-side: an expired query is failed at group formation
    # without wasting a dispatch
    fut2 = s.submit(_q3(*_mk_binding(cctx, rng, 60), lit=0.0917))
    time.sleep(0.1)
    singles0 = tracing.get_count("serve.singles")
    s.run_pending()
    assert isinstance(fut2.exception(timeout=1), QueryTimeoutError)
    assert tracing.get_count("serve.singles") == singles0
    assert s.stats()["leases"] == 0


# ----------------------------------------------------------------------
# close() leak fix (satellite 1) + error-path lease coverage (satellite 3)
# ----------------------------------------------------------------------
def test_close_fails_pending_typed_workerless(cctx, rng):
    s = ServeScheduler(cctx, auto_start=False)
    futs = [s.submit(_q3(*_mk_binding(cctx, rng, 60), lit=0.013))
            for _ in range(3)]
    assert s.stats()["leases"] == 3
    s.close()
    for f in futs:
        assert isinstance(f.exception(timeout=1), SchedulerClosedError)
    assert s.stats()["leases"] == 0 and s.stats()["inflight_bytes"] == 0
    with pytest.raises(SchedulerClosedError):
        s.submit(_q3(*_mk_binding(cctx, rng, 60)))


def test_close_fails_pending_typed_wedged_worker(cctx, rng, monkeypatch):
    """THE close()/drain() leak regression: the worker wedges mid-group,
    t.join(timeout) returns with it still alive, and the queued record
    must be failed typed + released — not silently stranded forever."""
    monkeypatch.setattr(sched_mod, "CLOSE_JOIN_TIMEOUT_S", 0.2)
    release = threading.Event()
    orig = ServeScheduler._run_group

    def wedge(self, group):
        release.wait(10)  # the worker is stuck on its first group
        return orig(self, group)

    monkeypatch.setattr(ServeScheduler, "_run_group", wedge)
    s = ServeScheduler(cctx, auto_start=True)
    f1 = s.submit(_q3(*_mk_binding(cctx, rng, 60), lit=0.017))
    deadline = time.monotonic() + 10
    while s.stats()["queue_depth"] and time.monotonic() < deadline:
        time.sleep(0.01)  # wait for the worker to take f1's group
    f2 = s.submit(
        _q3(*_mk_binding(cctx, rng, 60, key_hi=13), lit=0.017))
    s.close()  # join times out: f2 still queued, f1 held by the worker
    assert isinstance(f2.exception(timeout=1), SchedulerClosedError)
    # the IN-FLIGHT group is an orphan too: records in the wedged
    # worker's frame (not the queue) must not be stranded
    assert isinstance(f1.exception(timeout=1), SchedulerClosedError)
    assert s.stats()["queue_depth"] == 0
    assert s.stats()["leases"] == 0 and s.stats()["inflight_bytes"] == 0
    # close() rebalanced the wedged worker's _executing slot: a closed
    # scheduler must CONVERGE — drain() returns instead of parking
    # forever on a slot whose owner may never come back
    assert s.stats()["executing"] == 0
    assert s.drain(timeout=1) is True
    release.set()  # the worker unwedges: its late fulfill loses the
    # transition race, and nothing double-releases or goes negative
    t = s._thread
    if t is not None:  # the exiting worker publishes _thread=None (the
        t.join(timeout=30)  # liveness handshake); a caught reference
        assert not t.is_alive()  # must still drain within the timeout
    deadline = time.monotonic() + 30
    while s._thread is not None and time.monotonic() < deadline:
        time.sleep(0.01)
    assert s._thread is None  # exit published through the handshake
    assert isinstance(f1.exception(timeout=1), SchedulerClosedError)
    assert s.stats()["leases"] == 0 and s.stats()["inflight_bytes"] == 0
    # the late decrement consumed the rebalance token, not the counter
    assert s.stats()["executing"] == 0


def test_exec_error_releases_lease_and_gc_path(cctx, rng, monkeypatch):
    """Satellite 3: an exception between submit() and result() releases
    the admission lease at failure time; a dropped errored future leaks
    nothing through the GC finalizer either."""
    _arm(monkeypatch, "serve.single_exec:p=1")
    s = ServeScheduler(cctx, auto_start=False)
    fut = s.submit(_q3(*_mk_binding(cctx, rng, 70), lit=0.019))
    assert s.stats()["leases"] == 1
    s.run_pending()
    assert isinstance(fut.exception(timeout=5), QueryExecError)
    assert s.stats()["leases"] == 0 and s.stats()["inflight_bytes"] == 0
    with pytest.raises(QueryExecError):
        fut.result(timeout=5)
    # dropped-unconsumed errored future: the finalizer releases (again,
    # idempotently) and nothing goes negative or leaks
    fut2 = s.submit(_q3(*_mk_binding(cctx, rng, 60), lit=0.019))
    s.run_pending()
    del fut2
    gc.collect()
    assert s.stats()["leases"] == 0 and s.stats()["inflight_bytes"] == 0


def test_exception_timeout_contract(cctx, rng):
    """exception(timeout=) raises TimeoutError while unfulfilled (the
    query is still in flight — not failed), returns None on success."""
    s = ServeScheduler(cctx, auto_start=False)
    fut = s.submit(_q3(*_mk_binding(cctx, rng, 60), lit=0.023))
    with pytest.raises(TimeoutError):
        fut.exception(timeout=0.05)
    assert not fut.done()
    s.run_pending()
    assert fut.exception(timeout=5) is None
    fut.result(timeout=30)


def test_shed_reason_unconsumed_cap(cctx, rng, monkeypatch):
    """Satellite 3: the unconsumed_cap shed reason — results held past
    the 2x hard cap shed NEW submits, counted under their own reason."""
    ta, tb = _mk_binding(cctx, rng, 400)
    est = ct.serve.estimate_query_bytes([ta, tb])
    monkeypatch.setenv("CYLON_TPU_SERVE_INFLIGHT_BYTES", str(int(est * 1.2)))
    s = ServeScheduler(cctx, auto_start=False)
    c0 = tracing.get_count("serve.shed.unconsumed_cap")
    held = []
    shed = None
    for i in range(6):
        try:
            f = s.submit(
                _q3(*_mk_binding(cctx, rng, 400, key_hi=17), lit=0.029))
        except ServeOverloadError as e:
            shed = e
            break
        s.run_pending()
        held.append(f)  # fulfilled, never consumed: bytes stay held
    assert shed is not None and shed.retryable
    assert tracing.get_count("serve.shed.unconsumed_cap") == c0 + 1
    for f in held:
        f.result(timeout=30)
    assert s.stats()["inflight_bytes"] == 0 and s.stats()["leases"] == 0


def test_errors_feed_slo_rule(cctx, rng, monkeypatch):
    """The new error-rate SLO rule: typed failures drive errors ->
    WARN/BREACH and age out with the window (the /healthz substrate)."""
    from cylon_tpu.obs import slo

    m = slo.SLOMonitor(window=0.25)
    assert m.evaluate().get("errors") == slo.STATE_OK
    monkeypatch.setenv("CYLON_TPU_SERVE_BATCH_MAX", "1")  # singles path
    _arm(monkeypatch, "serve.single_exec:p=1")
    s = ServeScheduler(cctx, auto_start=False)
    futs = [s.submit(_q3(*_mk_binding(cctx, rng, 60), lit=0.031))
            for _ in range(3)]
    s.run_pending()
    for f in futs:
        assert f.exception(timeout=5) is not None
    assert m.evaluate()["errors"] == slo.STATE_BREACH
    ok, reasons = m.healthy()
    assert not ok and any(r.startswith("errors=") for r in reasons)
    time.sleep(0.3)
    assert m.evaluate()["errors"] == slo.STATE_OK  # aged out


# ----------------------------------------------------------------------
# spill: the I/O degradation ladder + stale-dir reclamation (satellite 2)
# ----------------------------------------------------------------------
def test_spill_write_retry_heals(monkeypatch, tmp_path):
    """A transient ENOSPC heals inside CYLON_TPU_SPILL_RETRIES with the
    arena rolled back to the batch boundary (no double-append)."""
    monkeypatch.setenv("CYLON_TPU_SPILL_DIR", str(tmp_path))
    monkeypatch.setenv("CYLON_TPU_SPILL_RETRIES", "2")
    _arm(monkeypatch, "spill.write:p=1:n=2")
    r0 = tracing.get_count("shuffle.spill.io_retries")
    sink = spill_mod.ShardArenaSink(
        2, [("a", np.dtype(np.int32), False)], spill_mod.TIER_DISK)
    data = np.arange(64, dtype=np.int32)
    sink.accept(None, [[(data, None)], [(data * 2, None)]],
                np.array([64, 64]))
    assert tracing.get_count("shuffle.spill.io_retries") == r0 + 2
    got = [sink.arenas[s].columns()[0][0] for s in (0, 1)]
    assert np.array_equal(got[0], data) and np.array_equal(got[1], data * 2)
    assert list(sink.counts()) == [64, 64]  # rollback: no double-append
    sink.close()


def test_spill_write_degrades_to_host_then_types(monkeypatch, tmp_path):
    monkeypatch.setenv("CYLON_TPU_SPILL_DIR", str(tmp_path))
    monkeypatch.setenv("CYLON_TPU_SPILL_RETRIES", "1")
    _arm(monkeypatch, "spill.write:p=1")  # the volume NEVER recovers
    d0 = tracing.get_count("shuffle.spill.tier_degraded")
    sink = spill_mod.ShardArenaSink(
        1, [("a", np.dtype(np.int64), True)], spill_mod.TIER_DISK)
    data = np.arange(32, dtype=np.int64)
    sink.accept(None, [[(data, None)]], np.array([32]))
    assert tracing.get_count("shuffle.spill.tier_degraded") == d0 + 1
    assert sink.arenas[0]._no_disk  # re-planned onto the host tier
    assert np.array_equal(sink.arenas[0].columns()[0][0], data)
    sink.close()
    # with the host tier ALSO failing (arena.alloc), the ladder is out
    # of rungs: typed SpillIOError, arenas closed by the caller
    _arm(monkeypatch, "arena.alloc:p=1")
    sink2 = spill_mod.ShardArenaSink(
        1, [("a", np.dtype(np.int64), False)], spill_mod.TIER_HOST)
    with pytest.raises(SpillIOError) as ei:
        sink2.accept(None, [[(data, None)]], np.array([32]))
    assert ei.value.scope == "query" and ei.value.retryable
    sink2.close()


def test_degraded_arena_respects_host_budget(monkeypatch, tmp_path):
    """A disk-degraded arena (_no_disk) must NOT grow host RAM past
    CYLON_TPU_SPILL_HOST_BUDGET — its disk escape is gone, so a budget
    breach fails typed instead of marching toward a host OOM."""
    monkeypatch.setenv("CYLON_TPU_SPILL_DIR", str(tmp_path))
    monkeypatch.setenv("CYLON_TPU_SPILL_RETRIES", "0")
    _arm(monkeypatch, "spill.write:p=1")  # the volume never recovers
    sink = spill_mod.ShardArenaSink(
        1, [("a", np.dtype(np.int64), False)], spill_mod.TIER_DISK)
    data = np.arange(64, dtype=np.int64)
    sink.accept(None, [[(data, None)]], np.array([64]))
    assert sink.arenas[0]._no_disk  # degraded under an open budget
    # now close the budget below what's already live: the next growth
    # on the degraded arena must ride the ladder to a typed failure
    live = spill_mod.arena_bytes()[0]
    monkeypatch.setenv("CYLON_TPU_SPILL_HOST_BUDGET", str(max(live, 1)))
    big = np.arange(4096, dtype=np.int64)
    with pytest.raises(SpillIOError) as ei:
        sink.accept(None, [[(big, None)]], np.array([4096]))
    assert ei.value.scope == "query"
    assert list(sink.counts()) == [64]  # rollback: the batch never landed
    sink.close()
    assert spill_mod.arena_bytes()[0] == 0


@pytest.mark.slow  # e2e spilled joins x3; CI chaos-smoke pins the same
def test_spilled_join_identical_under_write_faults(cctx, rng, monkeypatch,
                                                   tmp_path):
    """End to end: a forced-tier-2 join under a 100%-failing spill
    volume degrades to the host tier and returns the EXACT tier-0
    result; arena bytes return to baseline."""
    ta = ct.Table.from_pydict(cctx, {
        "k": rng.integers(0, 60, 3000).astype(np.int64),
        "v": rng.integers(-9, 9, 3000).astype(np.int32)})
    tb = ct.Table.from_pydict(cctx, {
        "rk": rng.integers(0, 60, 3000).astype(np.int64),
        "w": rng.integers(-9, 9, 3000).astype(np.int32)})
    oracle = _canon(ta.distributed_join(tb, left_on=["k"], right_on=["rk"]))
    monkeypatch.setenv("CYLON_TPU_SPILL_TIER", "2")
    monkeypatch.setenv("CYLON_TPU_SPILL_DIR", str(tmp_path))
    for seam in ("spill.write:p=1", "spill.read:p=1"):
        _arm(monkeypatch, seam)
        got = _canon(ta.distributed_join(tb, left_on=["k"], right_on=["rk"]))
        assert got == oracle, f"diverged under {seam}"
        assert fault.fired(seam.split(":")[0]) > 0
    gc.collect()
    live, _pk, disk, _dp = spill_mod.arena_bytes()
    assert live == 0 and disk == 0


def test_spilled_join_types_when_ladder_exhausted(cctx, rng, monkeypatch,
                                                  tmp_path):
    """Alloc failing on every tier: the query fails with SpillIOError —
    query-scoped, arenas closed — and the engine survives to run the
    same join cleanly right after."""
    ta = ct.Table.from_pydict(cctx, {
        "k": rng.integers(0, 50, 2000).astype(np.int64),
        "v": rng.integers(-9, 9, 2000).astype(np.int32)})
    tb = ct.Table.from_pydict(cctx, {
        "rk": rng.integers(0, 50, 2000).astype(np.int64),
        "w": rng.integers(-9, 9, 2000).astype(np.int32)})
    oracle = _canon(ta.distributed_join(tb, left_on=["k"], right_on=["rk"]))
    monkeypatch.setenv("CYLON_TPU_SPILL_TIER", "1")
    monkeypatch.setenv("CYLON_TPU_SPILL_DIR", str(tmp_path))
    monkeypatch.setenv("CYLON_TPU_SPILL_RETRIES", "0")
    _arm(monkeypatch, "arena.alloc:p=1")
    with pytest.raises(SpillIOError):
        ta.distributed_join(tb, left_on=["k"], right_on=["rk"])
    gc.collect()
    live, _pk, disk, _dp = spill_mod.arena_bytes()
    assert live == 0 and disk == 0  # the failure path closed the sinks
    monkeypatch.delenv("CYLON_TPU_FAULTS")
    fault.reset()
    got = _canon(ta.distributed_join(tb, left_on=["k"], right_on=["rk"]))
    assert got == oracle  # the process (and context) are untouched


@pytest.mark.slow  # two full ooc joins; the unit ladder tests stay fast
def test_ooc_join_types_spill_faults(cctx, rng, monkeypatch, tmp_path):
    """The out-of-core join's caller-owned arenas have no in-line retry
    ladder — a spill fault there must still leave as a typed
    SpillIOError with every arena (ingest AND result) closed."""
    import pandas as pd

    from cylon_tpu.parallel.ooc import OutOfCoreJoin

    monkeypatch.setenv("CYLON_TPU_SPILL_DIR", str(tmp_path))
    ldf = pd.DataFrame({
        "k": rng.integers(0, 500, 4000).astype(np.int32),
        "v": rng.normal(size=4000).astype(np.float32)})
    rdf = pd.DataFrame({
        "k": rng.integers(0, 500, 4000).astype(np.int32),
        "w": rng.normal(size=4000).astype(np.float32)})

    def chunks(df, n):
        for lo in range(0, len(df), n):
            yield {c: df[c].to_numpy()[lo:lo + n] for c in df.columns}

    monkeypatch.setenv("CYLON_TPU_SPILL_RETRIES", "0")
    monkeypatch.setenv("CYLON_TPU_SPILL_TIER", "2")
    _arm(monkeypatch, "arena.alloc:p=1")
    job = OutOfCoreJoin(cctx, on="k", how="inner", num_buckets=4)
    with pytest.raises(SpillIOError):
        job.execute(chunks(ldf, 1000), chunks(rdf, 1000))
    gc.collect()
    live, _pk, disk, _dp = spill_mod.arena_bytes()
    assert live == 0 and disk == 0
    # the engine survives: the same join runs clean right after
    monkeypatch.delenv("CYLON_TPU_FAULTS")
    fault.reset()
    job2 = OutOfCoreJoin(cctx, on="k", how="inner", num_buckets=4)
    sink = job2.execute(chunks(ldf, 1000), chunks(rdf, 1000))
    assert sink.rows == len(ldf.merge(rdf, on="k"))
    sink.close()


def test_reap_stale_spill_dirs(tmp_path):
    """Satellite 2: dead-pid spill dirs are reclaimed (age-guarded);
    live-pid, fresh, and unparseable dirs are left alone."""
    proc = subprocess.Popen(["true"])
    proc.wait()
    dead_pid = proc.pid  # provably dead, freshly reaped
    pfx = spill_mod.SPILL_DIR_PREFIX
    host = spill_mod._host_tag()
    orphan = tmp_path / f"{pfx}{host}-{dead_pid}_abc"
    fresh = tmp_path / f"{pfx}{host}-{dead_pid}_fresh"
    mine = tmp_path / f"{pfx}{host}-{os.getpid()}_live"
    # a shared (NFS) volume: another HOST's dir, same dead pid number —
    # its pid namespace is not ours, so it must never be reaped
    foreign = tmp_path / f"{pfx}otherhost-{dead_pid}_x"
    legacy = tmp_path / f"{pfx}notapid"
    for d in (orphan, fresh, mine, foreign, legacy):
        d.mkdir()
        (d / "col1.bin").write_bytes(b"x" * 128)
    old = time.time() - 3600
    os.utime(orphan, (old, old))
    os.utime(foreign, (old, old))
    assert spill_mod.reap_stale_spill(str(tmp_path), min_age_s=60) == 1
    assert not orphan.exists()
    assert fresh.exists() and mine.exists() and legacy.exists()
    assert foreign.exists()
    # context-init entry point: runs against the configured dir, never
    # raises (smoke: an unreadable dir is a no-op)
    assert spill_mod.reap_stale_spill("/nonexistent-dir-xyz") == 0


def test_arena_dirs_are_pid_stamped(monkeypatch, tmp_path):
    monkeypatch.setenv("CYLON_TPU_SPILL_DIR", str(tmp_path))
    a = spill_mod.HostArena(
        [("a", np.dtype(np.int32), False)], spill_mod.TIER_DISK)
    a.append_batch([(np.arange(8, dtype=np.int32), None)])
    dirs = list(tmp_path.iterdir())
    assert len(dirs) == 1
    assert dirs[0].name.startswith(
        f"{spill_mod.SPILL_DIR_PREFIX}"
        f"{spill_mod._host_tag()}-{os.getpid()}_")
    a.close()
    assert not dirs[0].exists()  # close still removes its own dir


# ----------------------------------------------------------------------
# obs: journal degrade
# ----------------------------------------------------------------------
def test_obs_journal_degrades_to_memory(monkeypatch, tmp_path):
    from cylon_tpu.obs import metrics as obsmetrics
    from cylon_tpu.obs.store import ObsStore

    _arm(monkeypatch, "obs.journal:p=1")
    c0 = obsmetrics.get_count("obs.journal_degraded")
    st = ObsStore(str(tmp_path), writer_id="t1")
    for i in range(5):
        st.record({"k": "lat", "fp": "fp1", "s": 0.01 * (i + 1)})
    assert st.journal_degraded
    assert obsmetrics.get_count("obs.journal_degraded") == c0 + 1  # once
    # in-memory telemetry kept flowing: the profile absorbed everything
    assert st.profiles["fp1"]["lat"]["n"] == 5
    # ...but nothing was persisted (the volume is gone)
    assert os.path.getsize(st.journal_path) == 0 if os.path.exists(
        st.journal_path) else True
    st.close()
