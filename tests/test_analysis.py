"""graft-lint: the static invariant analyzer (ISSUE 6).

Covers both layers:

- AST pass: the four seeded known-bad fixtures (tests/lint_fixtures) are
  flagged with the right rule at the right site; the known-good twins
  and the LIVE TREE are clean; the exemption registry holds zero blanket
  entries.
- jaxpr pass: collective census mechanics (scan scaling, host-callback
  detection), the extra-collective and mid-loop-sync seeded violations,
  the fused join / q3 step contracts (pure trace, no execution), and —
  slow-marked, CI runs it via ``python -m tools.graft_lint`` — the full
  representative-plan registry.

The hand-written collective pins in test_shuffle_chunked.py /
test_semi_filter.py re-export their numbers from
``cylon_tpu.analysis.contracts``; this file pins the contract table's
own shape so those constants cannot drift silently.
"""
import os

import numpy as np
import pytest

import cylon_tpu as ct
from cylon_tpu.analysis import contracts
from cylon_tpu.analysis.ast_pass import (
    check_no_blanket_exemptions,
    run_ast_pass,
)
from cylon_tpu.analysis.jaxpr_pass import Census, census_fn
from cylon_tpu.analysis.hostsync import sync_monitor

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURES = os.path.join(HERE, "lint_fixtures")
TREE = os.path.join(os.path.dirname(HERE), "cylon_tpu")


def _fixture_findings(name):
    return run_ast_pass(FIXTURES, files=[os.path.join(FIXTURES, name)])


# ----------------------------------------------------------------------
# AST pass: seeded fixtures
# ----------------------------------------------------------------------
def test_bad_gate_not_in_key_flagged():
    fs = _fixture_findings("bad_gate_not_in_key.py")
    assert len(fs) == 1, fs
    f = fs[0]
    assert f.rule == "gate-not-in-key"
    assert f.name == "CYLON_TPU_REPEAT_IMPL"
    assert "bad_gate_not_in_key" in f.func


def test_bad_baked_constant_flagged():
    fs = _fixture_findings("bad_baked_constant.py")
    assert len(fs) == 1, fs
    f = fs[0]
    assert f.rule == "baked-constant"
    assert f.name == "threshold"
    assert "kern" in f.func


def test_good_twins_clean():
    """The same shapes with the invariant held: taint into the key, the
    scalar as a key component, the declarative site comment."""
    assert _fixture_findings("good_cases.py") == []


def test_live_tree_clean():
    """The acceptance gate: zero findings over cylon_tpu/ itself."""
    fs = run_ast_pass(TREE, package="cylon_tpu")
    assert fs == [], "\n".join(str(f) for f in fs)


def test_no_blanket_exemptions():
    """Every registry exemption names a concrete gate and an audited
    reason; `# lint:` comments are site-scoped by construction."""
    assert check_no_blanket_exemptions() == []
    from cylon_tpu.analysis.registry import EXEMPT

    for (scope, var), reason in EXEMPT.items():
        assert var.startswith("CYLON_TPU_"), (scope, var)
        assert len(reason) >= 20, (scope, var)


def test_relative_import_resolution_in_package_init():
    """Regression: a package __init__'s dotted name IS its package, so
    `from .utils import envgate` in cylon_tpu/__init__.py must resolve
    to cylon_tpu.utils.envgate (dropping one fewer level than a plain
    module would) — getting this wrong silently loses analyzer edges."""
    from cylon_tpu.analysis.ast_pass import _resolve_relative

    assert (
        _resolve_relative("cylon_tpu", 1, "utils", is_pkg=True)
        == "cylon_tpu.utils"
    )
    assert (
        _resolve_relative("cylon_tpu.table", 1, "utils", is_pkg=False)
        == "cylon_tpu.utils"
    )
    assert (
        _resolve_relative("cylon_tpu.ops.join", 2, "utils.envgate")
        == "cylon_tpu.utils.envgate"
    )


def test_cyclic_helpers_keep_transitive_reads(tmp_path):
    """Regression: mutually recursive helpers must not memoize a partial
    read-set computed while the cycle was open — the gate read through
    the cycle must still reach the key-builder check."""
    src = tmp_path / "cyc.py"
    src.write_text(
        "import os\n"
        "from cylon_tpu.engine import get_kernel\n\n"
        "def f(n):\n"
        "    if n > 0:\n"
        "        return g(n - 1)\n"
        "    return os.environ.get('CYLON_TPU_REPEAT_IMPL', 'scatter')\n\n"
        "def g(n):\n"
        "    return f(n)\n\n"
        "def builder_fn(ctx, cols):\n"
        "    key = ('cyc', len(cols))\n\n"
        "    def build():\n"
        "        def kern(dp, rep):\n"
        "            return g(0)\n\n"
        "        return kern\n\n"
        "    return get_kernel(ctx, key, build)(cols, ())\n"
    )
    fs = run_ast_pass(str(tmp_path), files=[str(src)])
    assert any(
        f.rule == "gate-not-in-key" and f.name == "CYLON_TPU_REPEAT_IMPL"
        for f in fs
    ), fs


def test_unregistered_env_read_flagged(tmp_path):
    src = tmp_path / "rogue.py"
    src.write_text(
        "import os\n\n"
        "def rogue():\n"
        "    return os.environ.get('CYLON_TPU_BRAND_NEW_KNOB', '0')\n"
    )
    fs = run_ast_pass(str(tmp_path), files=[str(src)])
    assert [f.rule for f in fs] == ["unregistered-env-read"]
    assert fs[0].name == "CYLON_TPU_BRAND_NEW_KNOB"


# ----------------------------------------------------------------------
# jaxpr pass: census mechanics + seeded violations
# ----------------------------------------------------------------------
def _mesh4(devices):
    from jax.sharding import Mesh

    return Mesh(np.array(devices[:4]), ("dp",))


def _shard_fn(devices, body):
    import jax
    from jax.sharding import PartitionSpec as P

    from cylon_tpu.compat import shard_map

    return jax.jit(
        shard_map(
            body,
            mesh=_mesh4(devices),
            in_specs=(P("dp"),),
            out_specs=P("dp"),
        )
    )


def test_extra_collective_fixture_flagged(devices):
    """Seeded known-bad: a step that issues 3 all_to_alls against a
    2-collective contract."""
    import jax
    import jax.numpy as jnp

    def body(x):
        for _ in range(3):
            x = jax.lax.all_to_all(
                x.reshape(4, -1), "dp", 0, 0, tiled=False
            ).reshape(-1)
        return x

    cen = census_fn(
        _shard_fn(devices, body), jax.ShapeDtypeStruct((32,), jnp.int32)
    )
    assert cen.counts == {"all_to_all": 3}
    c = contracts.CollectiveContract(
        name="fixture_extra_coll", description="", collectives=2, all_to_all=2
    )
    viol = c.check(cen)
    assert len(viol) == 2 and "all_to_all = 3" in viol[1], viol


def test_census_scales_scan_rounds(devices):
    """A K-round fused loop in ONE program counts K collectives (the scan
    body is scaled by its trip count, like the roofline walker)."""
    import jax
    import jax.numpy as jnp

    def body(x):
        def round_(carry, _):
            y = jax.lax.all_to_all(
                carry.reshape(4, -1), "dp", 0, 0, tiled=False
            ).reshape(-1)
            return y, ()

        out, _ = jax.lax.scan(round_, x, None, length=5)
        return out

    cen = census_fn(
        _shard_fn(devices, body), jax.ShapeDtypeStruct((32,), jnp.int32)
    )
    assert cen.counts == {"all_to_all": 5}


def test_host_callback_detected():
    """In-program host transfers (callback primitives) violate every
    contract — no shipped kernel may round-trip to the host."""
    import jax
    import jax.numpy as jnp

    def body(x):
        return jax.pure_callback(
            lambda a: np.asarray(a), jax.ShapeDtypeStruct(x.shape, x.dtype), x
        )

    cen = census_fn(jax.jit(body), jax.ShapeDtypeStruct((8,), jnp.float32))
    assert cen.host_callbacks
    viol = contracts.CONTRACTS["shuffle_single"].check(cen, k=0)
    assert any("host-callback" in v for v in viol)


def test_midloop_sync_fixture_flagged(devices):
    """Seeded known-bad: a dispatch loop that fetches EVERY round. The
    monitor attributes each fetch; the contract flags both the
    non-whitelisted site and the K-scaling sync count."""
    import jax.numpy as jnp

    from cylon_tpu import table as _t

    def bad_round_loop(bufs):
        out = []
        for b in bufs:  # one host sync per round — the anti-pattern
            out.append(_t._fetch(b))
        return out

    with sync_monitor() as events:
        bad_round_loop([jnp.zeros((4,)) for _ in range(4)])
    cen = Census(counts={"all_to_all": 4})
    viol = contracts.CONTRACTS["shuffle_single"].check(
        cen, k=4, sync_events=events
    )
    assert any("host syncs" in v for v in viol), viol
    assert any("outside the whitelisted sites" in v for v in viol), viol
    assert all(e.site == "bad_round_loop" for e in events)


# ----------------------------------------------------------------------
# contract table: the numbers the pin tests re-export
# ----------------------------------------------------------------------
def test_contract_constants_pinned():
    assert contracts.DIST_JOIN_PAYLOAD_COLLECTIVES == 2
    assert contracts.DIST_JOIN_SKETCH_COLLECTIVES == 1
    assert contracts.shuffle_collectives(7) == 7
    assert contracts.fused_join_collectives(2) == 8
    assert contracts.fused_q3_collectives(1) == 7
    assert contracts.SHUFFLE_HOST_SYNCS_PER_TABLE == 2
    assert "_shuffle_many" in contracts.SHUFFLE_SYNC_SITES


def test_fused_step_contracts_trace_only(ctx8):
    """The fused join + q3 step contracts hold by pure jaxpr census (no
    execution — this also pins the q3 path's collective count, the
    acceptance criterion)."""
    from cylon_tpu.analysis import plans

    for res in plans.run_fused_join_step(ctx8, None):
        assert res.violations == [], res.violations
    for res in plans.run_q3_fused_step(ctx8, None):
        assert res.violations == [], res.violations


def test_shuffle_contract_runtime(ctx8, rng):
    """One runtime plan in tier-1: the K-round shuffle's census + sync
    whitelist (K = 1 and K > 1; the deferred fetch stays ONE fetch)."""
    from cylon_tpu.analysis import plans

    for res in plans.run_shuffle_single(ctx8, rng):
        assert res.violations == [], (res.k, res.violations)
        # count-phase fetch in _shuffle_many; the ONE deferred round
        # fetch in _shuffle_many_rounds (phase 2, split out by the
        # ISSUE-14 failure-domain wrapper)
        assert res.sync_sites == ["_shuffle_many", "_shuffle_many_rounds"]


@pytest.mark.slow
def test_full_plan_registry(ctx8, rng):
    """Every representative plan vs the contract table (CI runs this via
    `python -m tools.graft_lint`; slow-marked for tier-1)."""
    from cylon_tpu.analysis import plans

    results = plans.run_all(ctx=ctx8)
    bad = [v for r in results for v in r.violations]
    assert bad == [], bad


# ----------------------------------------------------------------------
# Layer 3: effect inference + sync-freedom certification (ISSUE 7)
# ----------------------------------------------------------------------
def _effect_findings(name, budgets=None, signatures=None):
    from cylon_tpu.analysis.syncfree import run_effect_pass

    return run_effect_pass(
        FIXTURES,
        files=[os.path.join(FIXTURES, name)],
        budgets={} if budgets is None else budgets,
        signatures=signatures,
    )


def test_bad_hidden_fetch_flagged():
    """Seeded known-bad: a fetch hidden behind TWO call hops must fail
    the entry's 0-site sync budget AND drift its pinned signature, with
    the full call path in both messages."""
    fs, reports = _effect_findings(
        "bad_hidden_fetch.py",
        budgets={"collect_stats": contracts.SyncBudget(0)},
        signatures={"collect_stats": "DISPATCH_SAFE"},
    )
    assert sorted(f.rule for f in fs) == ["effect-drift", "sync-budget"], fs
    for f in fs:
        assert "collect_stats -> _tally -> _sum_counts" in f.message, f
    assert reports["collect_stats"].signature == "SYNC"
    [site] = reports["collect_stats"].sync_sites
    assert site.kind == "fetch" and site.line == 21


def test_bad_shared_write_flagged():
    """Seeded known-bad: an unguarded module-dict write reachable from a
    public entry is a finding (the concurrent-serving data race)."""
    fs, _ = _effect_findings("bad_shared_write.py")
    assert [f.rule for f in fs] == ["unguarded-shared-write"], fs
    assert fs[0].name == "_RESULT_CACHE[...]"
    assert fs[0].func.endswith("remember")


def test_effect_good_twins_clean():
    """The same shapes with the invariant held: lock-dominated write,
    GIL-atomic setdefault publish, `# lint: guarded=` / `# lint:
    sync=host` declarations, and a genuinely dispatch-safe chain."""
    fs, reports = _effect_findings("good_effect_cases.py")
    assert fs == [], fs
    assert reports["dispatch_chain"].signature == "DISPATCH_SAFE"
    assert reports["remember_locked"].signature == "DISPATCH_SAFE"


def test_live_tree_effect_clean():
    """The L3 acceptance gate: zero effect findings over cylon_tpu/ —
    every public entry matches its pinned signature, every sync budget
    holds exactly, no unguarded shared writes anywhere."""
    from cylon_tpu.analysis.syncfree import run_effect_pass

    fs, reports = run_effect_pass(TREE, package="cylon_tpu")
    assert fs == [], "\n".join(str(f) for f in fs)
    # every certified entry is pinned; no MUTATES_SHARED flag anywhere
    assert set(reports) == set(contracts.EFFECT_SIGNATURES)
    assert all("MUTATES_SHARED" not in r.signature for r in reports.values())


def test_l3_contract_constants_pinned():
    """The sync-budget numbers the runtime pins re-export."""
    assert contracts.EAGER_OP_HOST_SYNCS == 0
    assert contracts.Q3_DISPATCH_HOST_SYNCS == 1
    assert contracts.Q3_DISPATCH_SYNC_SITES == ("_materialize_counts",)
    for op in contracts.Q3_DISPATCH_OPS:
        assert contracts.SYNC_SITE_BUDGETS[op].sites == 0, op
    assert (
        contracts.SYNC_SITE_BUDGETS["table._shuffle_many"].sites
        == contracts.SHUFFLE_HOST_SYNCS_PER_TABLE
    )
    assert contracts.SYNC_SITE_BUDGETS["Table._materialize_counts"].amortized
    # the flagship signatures: the q3 components are dispatch-async
    assert contracts.EFFECT_SIGNATURES["Table.project"] == "DISPATCH_SAFE"
    assert "SYNC" not in contracts.EFFECT_SIGNATURES["Table.filter"]
    assert "SYNC" not in contracts.EFFECT_SIGNATURES["Table.groupby"]
    assert contracts.CONTRACTS["q3_dispatch"].sync_sites == (
        "_materialize_counts",
    )


def test_eager_sync_free_runtime(ctx8, rng):
    """Runtime twin of the 0-site budgets: filter/groupby/unique
    dispatch with ZERO monitored fetches."""
    from cylon_tpu.analysis import plans

    for res in plans.run_eager_sync_free(ctx8, rng):
        assert res.violations == [], res.violations
        assert res.sync_sites == []


def test_q3_dispatch_runtime(ctx8, rng):
    """THE ISSUE-7 acceptance pin at runtime: a fused q3 plan
    dispatch()es with zero host syncs and materializes with exactly one,
    attributed to _materialize_counts."""
    from cylon_tpu.analysis import plans

    for res in plans.run_q3_dispatch(ctx8, rng):
        assert res.violations == [], res.violations
        assert res.sync_sites == ["_materialize_counts"]


def test_graft_lint_json_effects(capsys):
    """--json emits one machine-readable object (the CI artifact)."""
    import json as _json

    from tools import graft_lint

    rc = graft_lint.main(["--effects-only", "--json"])
    out = capsys.readouterr().out
    doc = _json.loads(out)
    assert rc == 0 and doc["exit_status"] == 0
    eff = doc["layers"]["effects"]
    assert eff["findings"] == []
    assert len(eff["signatures"]) == len(contracts.EFFECT_SIGNATURES)
    assert (
        eff["signatures"]["Table.project"]["signature"] == "DISPATCH_SAFE"
    )


def test_no_effect_lint_kill_switch(capsys, monkeypatch):
    """CYLON_TPU_NO_EFFECT_LINT=1 skips Layer 3 (declared in envgate —
    an incident escape hatch, surfaced loudly in the output)."""
    import json as _json

    from tools import graft_lint

    monkeypatch.setenv("CYLON_TPU_NO_EFFECT_LINT", "1")
    rc = graft_lint.main(["--effects-only", "--json"])
    doc = _json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["layers"]["effects"] == {"skipped": True}
