"""Round-4 surface-tail parity (VERDICT r3 item 7): DURATION + the
unsupported enum tail, ParquetOptions, CSVWriteOptions breadth, and the
Table/DataFrame method aliases the reference exposes
(reference: data_types.hpp:55-82, io/parquet_config.hpp,
io/csv_write_config.hpp, python/pycylon/data/table.pyx, pycylon/frame.py).
"""
import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu import dtypes


def test_duration_roundtrip(local_ctx):
    td = np.array([1, -5, 3600], dtype="timedelta64[s]")
    t = ct.Table.from_pydict(local_ctx, {"d": td})
    assert t.dtype_of("d").type == dtypes.Type.DURATION
    out = t.to_pandas()["d"].to_numpy()
    assert (out == td.astype("timedelta64[ns]")).all()
    # arrow bridge both ways
    at = t.to_arrow()
    back = ct.Table.from_arrow(local_ctx, at)
    assert back.dtype_of("d").type == dtypes.Type.DURATION
    assert (back.to_pandas()["d"].to_numpy() == td.astype("timedelta64[ns]")).all()


def test_duration_null_roundtrip(local_ctx):
    td = np.array([1, "NaT", 3], dtype="timedelta64[s]")
    t = ct.Table.from_pydict(local_ctx, {"d": td})
    out = t.to_pandas()["d"]
    assert out.isna().tolist() == [False, True, False]


def test_duration_sort(local_ctx):
    td = np.array([30, 10, 20], dtype="timedelta64[s]")
    t = ct.Table.from_pydict(local_ctx, {"d": td})
    got = t.sort("d").to_pandas()["d"].to_numpy()
    assert (np.diff(got).astype(np.int64) >= 0).all()


def test_unsupported_enum_tail_rejects():
    # every reference enum value exists; the non-representable tail fails
    # loudly at physical_dtype, never silently
    for name in ("FIXED_SIZE_BINARY", "INTERVAL", "DECIMAL", "LIST",
                 "EXTENSION", "FIXED_SIZE_LIST"):
        dt = dtypes.DataType(dtypes.Type[name])
        with pytest.raises(dtypes.UnsupportedTypeError):
            dt.physical_dtype
    # DURATION is in the tail positionally but fully supported
    assert dtypes.duration().physical_dtype == np.dtype(np.int64)


def test_table_name_aliases(local_ctx):
    t = ct.Table.from_pydict(local_ctx, {"a": np.arange(4), "b": np.arange(4.0)})
    assert t.add_prefix("p_").column_names == ["p_a", "p_b"]
    assert t.add_suffix("_s").column_names == ["a_s", "b_s"]
    s = t.to_string(row_limit=2)
    assert "..." in s or "." * 5 in s
    full = ct.Table.from_pydict(local_ctx, {"a": np.arange(2)}).to_string()
    assert "a" in full and "1" in full


def test_table_dropna_reference_axis(local_ctx):
    # reference table.pyx:2144: axis=0 drops COLUMNS with nulls, axis=1 ROWS
    t = ct.Table.from_pydict(
        local_ctx,
        {"a": np.array([1.0, np.nan, 3.0]), "b": np.array([4.0, 5.0, 6.0])},
    )
    assert t.dropna(axis=0, how="any").column_names == ["b"]
    assert t.dropna(axis=1, how="any").row_count == 2
    assert t.dropna(axis=0, how="all").column_names == ["a", "b"]
    # inplace mutates the receiver
    t2 = ct.Table.from_pydict(
        local_ctx, {"a": np.array([1.0, np.nan]), "b": np.array([1.0, 2.0])}
    )
    out = t2.dropna(axis=1, how="any", inplace=True)
    assert out is t2 and t2.row_count == 1


def test_table_isin_method(local_ctx):
    t = ct.Table.from_pydict(local_ctx, {"a": np.array([1, 2, 3])})
    got = t.isin([1, 3]).to_pandas()["a"].tolist()
    assert got == [True, False, True]


def test_table_applymap(local_ctx):
    t = ct.Table.from_pydict(local_ctx, {"a": np.array([1.0, 2.0])})
    got = t.applymap(lambda x: x + 10).to_pandas()["a"].tolist()
    assert got == [11.0, 12.0]


def test_table_concat_axis0(local_ctx):
    a = ct.Table.from_pydict(local_ctx, {"x": np.array([1, 2])})
    b = ct.Table.from_pydict(local_ctx, {"x": np.array([3])})
    got = ct.Table.concat([a, b], axis=0).to_pandas()["x"].tolist()
    assert sorted(got) == [1, 2, 3]


def test_table_concat_axis1(local_ctx):
    a = ct.Table.from_pydict(local_ctx, {"x": np.array([1, 2, 3])})
    b = ct.Table.from_pydict(local_ctx, {"y": np.array([10.0, 20.0, 30.0])})
    got = ct.Table.concat([a, b], axis=1)
    assert set(got.column_names) >= {"x", "y"}
    df = got.to_pandas().sort_values("x")
    assert df["y"].tolist() == [10.0, 20.0, 30.0]


def test_table_concat_axis1_indexed(local_ctx):
    a = ct.Table.from_pydict(
        local_ctx, {"k": np.array([2, 0, 1]), "x": np.array([20.0, 0.0, 10.0])}
    ).set_index("k")
    b = ct.Table.from_pydict(
        local_ctx, {"k": np.array([0, 1, 2]), "y": np.array([5.0, 6.0, 7.0])}
    ).set_index("k")
    got = ct.Table.concat([a, b], axis=1).to_pandas().sort_values("k")
    assert got["x"].tolist() == [0.0, 10.0, 20.0]
    assert got["y"].tolist() == [5.0, 6.0, 7.0]


def test_table_concat_axis1_name_collision(local_ctx):
    """Left data column named like the right index must survive (round-4
    review finding: the right-key drop used the user-visible name)."""
    a = ct.Table.from_pydict(
        local_ctx, {"a": np.array([0, 1]), "b": np.array([7.0, 8.0])}
    ).set_index("a")
    b = ct.Table.from_pydict(
        local_ctx, {"b": np.array([0, 1]), "c": np.array([1.0, 2.0])}
    ).set_index("b")
    got = ct.Table.concat([a, b], axis=1)
    df = got.to_pandas().sort_values("a")
    assert df["b"].tolist() == [7.0, 8.0]  # left data column intact
    assert df["c"].tolist() == [1.0, 2.0]


def test_table_concat_axis1_outer_coalesces_index(local_ctx):
    a = ct.Table.from_pydict(
        local_ctx, {"k": np.array([0, 1]), "x": np.array([1.0, 2.0])}
    ).set_index("k")
    b = ct.Table.from_pydict(
        local_ctx, {"k": np.array([1, 2]), "y": np.array([10.0, 20.0])}
    ).set_index("k")
    got = ct.Table.concat([a, b], axis=1, join="outer").to_pandas()
    # union of index values, no null index rows (right-only rows coalesced)
    assert sorted(got["k"].tolist()) == [0, 1, 2]


def test_table_dropna_inplace_invalidates_index(local_ctx):
    t = ct.Table.from_pydict(
        local_ctx,
        {"a": np.array([1.0, np.nan]), "b": np.array([1.0, 2.0])},
    ).set_index("a")
    t.dropna(axis=0, how="any", inplace=True)  # drops column 'a'
    assert t.index_name is None  # dangling index cleared


def test_table_add_prefix_keeps_index(local_ctx):
    t = ct.Table.from_pydict(
        local_ctx, {"a": np.array([1, 2]), "b": np.array([3, 4])}
    ).set_index("a")
    assert t.add_prefix("p_").index_name == "p_a"
    assert t.add_suffix("_s").index_name == "a_s"


def test_dataframe_concat_static(local_ctx):
    a = ct.DataFrame({"x": [1, 2]})
    b = ct.DataFrame({"x": [3]})
    got = ct.DataFrame.concat([a, b, None])
    assert sorted(got.to_pandas()["x"].tolist()) == [1, 2, 3]


def test_dataframe_add_suffix():
    df = ct.DataFrame({"a": [1], "b": [2]})
    assert df.add_suffix("_z").columns == ["a_z", "b_z"]


def test_parquet_options(tmp_path, local_ctx):
    df = pd.DataFrame({"a": np.arange(100), "b": np.arange(100.0)})
    t = ct.Table.from_pandas(local_ctx, df)
    p = str(tmp_path / "t.parquet")
    opts = ct.ParquetOptions().chunk_size(25).writer_properties(
        compression="snappy"
    )
    ct.write_parquet(t, p, opts)
    import pyarrow.parquet as pq

    meta = pq.ParquetFile(p).metadata
    assert meta.num_row_groups == 4  # 100 rows / chunk_size 25
    back = ct.read_parquet(local_ctx, p)
    pd.testing.assert_frame_equal(back.to_pandas(), df, check_dtype=False)
    # concurrent multi-file read path
    p2 = str(tmp_path / "t2.parquet")
    ct.write_parquet(t, p2)
    both = ct.read_parquet(
        local_ctx, [p, p2], ct.ParquetOptions().concurrent_file_reads(True)
    )
    assert both.row_count == 200


def test_csv_write_column_names(tmp_path, local_ctx):
    t = ct.Table.from_pydict(local_ctx, {"a": np.array([1, 2]), "b": np.array([3.5, 4.5])})
    p = str(tmp_path / "o.csv")
    opts = ct.CSVWriteOptions().with_column_names(["x", "y"])
    ct.write_csv(t, p, opts)
    back = pd.read_csv(p)
    assert list(back.columns) == ["x", "y"]
    assert back["x"].tolist() == [1, 2]
    with pytest.raises(ValueError):
        ct.write_csv(t, p, ct.CSVWriteOptions().with_column_names(["only_one"]))


def test_table_alias_methods(local_ctx):
    """Round-4 second surface pass: get_index/context/isna/notna/merge/
    to_csv/clear (reference table.pyx method diff)."""
    t = ct.Table.from_pydict(
        local_ctx, {"a": np.array([1.0, np.nan]), "b": np.array([1, 2])}
    )
    assert t.context is t.ctx
    assert t.get_index() is not None
    assert t.isna().to_pandas()["a"].tolist() == [False, True]
    assert t.notna().to_pandas()["a"].tolist() == [True, False]
    m = ct.Table.merge([t.drop(["a"]), t.drop(["a"])])
    assert m.row_count == 4
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        p = f"{tmp}/t.csv"
        t.to_csv(p)
        back = pd.read_csv(p)
        assert list(back.columns) == ["a", "b"] and len(back) == 2
    t.clear()
    assert t.column_count == 0 and t.row_count == 0


def test_compute_compare_array_like_values():
    from cylon_tpu import compute

    got = compute.compare_array_like_values(
        np.array([1.0, 2.0, np.nan]), [2.0, 3.0]
    )
    assert got.tolist() == [False, True, False]
    got = compute.compare_array_like_values(
        np.array(["x", "y"], dtype=object), ["y", "z"]
    )
    assert got.tolist() == [False, True]
    got = compute.compare_array_like_values(np.array([1, 2]), [])
    assert got.tolist() == [False, False]


def test_fused_join_respill_param(ctx8, rng):
    ldf = pd.DataFrame({"k": rng.integers(0, 50, 400).astype(np.int32),
                        "v": rng.normal(size=400).astype(np.float32)})
    rdf = pd.DataFrame({"k": rng.integers(0, 50, 300).astype(np.int32),
                        "w": rng.normal(size=300).astype(np.float32)})
    lt = ct.Table.from_pandas(ctx8, ldf)
    rt = ct.Table.from_pandas(ctx8, rdf)
    want = len(ldf.merge(rdf, on="k"))
    for resp in (0, 3):
        got = lt.distributed_join(
            rt, on="k", mode="fused", capacity_factor=0.25,
            respill=resp, max_retries=6,
        )
        assert got.row_count == want
    with pytest.raises(ValueError):
        lt.distributed_join(rt, on="k", mode="fused", respill=-1)

def test_to_string_wide_frame_keeps_all_column_blocks(local_ctx):
    # r4 advisor: pandas wraps wide frames into multiple column blocks; the
    # elided render must keep every block (line slicing used to cut them)
    cols = {f"column_{i:02d}": np.arange(40) * i for i in range(30)}
    t = ct.Table.from_pydict(local_ctx, cols)
    s = t.to_string(row_limit=4)
    for name in cols:
        assert name in s, name
    assert "..." in s


def test_compare_array_like_typed_membership():
    # r4 advisor: typed SetLookup semantics — int 1 must not match '1'
    from cylon_tpu.compute import compare_array_like_values

    vals = np.array([1, "1", "x", None], dtype=object)
    got = compare_array_like_values(vals, ["1", "x"])
    assert got.tolist() == [False, True, True, False]
    got = compare_array_like_values(vals, [1])
    assert got.tolist() == [True, False, False, False]
    # bytes unify with str; null matching only when skip_null=False
    got = compare_array_like_values(
        np.array(["a", None], dtype=object), [b"a", None], skip_null=False
    )
    assert got.tolist() == [True, True]


def test_dict_union_rejects_non_native_byte_order():
    # r4 advisor: a '>U' dictionary must fall back to numpy, not be
    # compared byteswapped by the native UCS4 merge
    from cylon_tpu.native import dict_union

    a = np.array(["a", "b"], dtype="<U4" if np.little_endian else ">U4")
    swapped = a.astype(a.dtype.newbyteorder())
    assert dict_union(swapped, a) is None
    assert dict_union(a, swapped) is None


def test_compare_array_like_unhashable_and_text_paths():
    from cylon_tpu.compute import compare_array_like_values

    # unhashable elements on either side must not raise (review r5):
    vals = np.array([[1, 2], "x", np.arange(3)], dtype=object)
    got = compare_array_like_values(vals, ["x"])
    assert got.tolist() == [False, True, False]
    got = compare_array_like_values(vals, [[1, 2], "x"])
    assert got.tolist() == [True, True, False]
    # pure-text dtypes take the vectorized path and drop non-text members
    got = compare_array_like_values(np.array(["1", "2"]), ["1", 2])
    assert got.tolist() == [True, False]
    got = compare_array_like_values(np.array([b"a", b"z"], dtype="S1"), ["a"])
    assert got.tolist() == [True, False]
