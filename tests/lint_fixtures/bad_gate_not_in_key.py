"""Seeded known-bad fixture (graft-lint rule ``gate-not-in-key``): the
kernel body reads CYLON_TPU_REPEAT_IMPL at trace time, but the cache key
never sees it — a mid-process flip would silently reuse the stale
program. tests/test_analysis.py asserts the AST pass flags exactly this.
"""
import os

from cylon_tpu.engine import get_kernel


def bad_gate_not_in_key(ctx, cols):
    key = ("fixture_bad_gate", len(cols))

    def build():
        def kern(dp, rep):
            if os.environ.get("CYLON_TPU_REPEAT_IMPL", "scatter") == "scatter":
                return dp
            return rep

        return kern

    return get_kernel(ctx, key, build)(cols, ())
