"""Seeded known-bad fixture (graft-lint L3 rule
``unguarded-shared-write``): a public entry point writes a module-level
dict — cross-query shared state — with no dominating lock and no
``# lint: guarded=`` declaration. Under concurrent query serving this is
a data race; tests/test_analysis.py asserts the effect pass flags it.
"""

_RESULT_CACHE = {}


def remember(key, value):
    _RESULT_CACHE[key] = value
    return value
