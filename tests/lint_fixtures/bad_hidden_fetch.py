"""Seeded known-bad fixture (graft-lint L3 rules ``sync-budget`` /
``effect-drift``): the public entry point looks sync-free, but a helper
two call hops down performs a device->host fetch. The effect pass must
classify ``collect_stats`` as SYNC with the full call-path attribution
(``collect_stats -> _tally -> _sum_counts``) and fail its 0-site sync
budget. tests/test_analysis.py asserts exactly this.
"""
from cylon_tpu.table import _fetch


def collect_stats(bufs):
    """Public entry: 'just' delegates... to a hidden sync."""
    return _tally(bufs)


def _tally(bufs):
    return _sum_counts(bufs)


def _sum_counts(bufs):
    return sum(int(_fetch(b)[0]) for b in bufs)
