"""Known-GOOD twins of the seeded bad fixtures: the same shapes with the
invariant held. The AST pass must stay silent on every function here —
tests/test_analysis.py asserts zero findings against this file.
"""
import os

from cylon_tpu.engine import get_kernel


def good_gate_threaded(ctx, cols):
    """The gate value is resolved on the host and TAINTS the key."""
    impl = os.environ.get("CYLON_TPU_REPEAT_IMPL", "scatter")
    key = ("fixture_good_gate", len(cols), impl)

    def build():
        def kern(dp, rep):
            if impl == "scatter":
                return dp
            return rep

        return kern

    return get_kernel(ctx, key, build)(cols, ())


def good_scalar_keyed(ctx, cols, threshold):
    """The captured scalar is a key component: a new value compiles a new
    program instead of aliasing the old one."""
    key = ("fixture_good_baked", len(cols), threshold)

    def build():
        def kern(dp, rep):
            (data, counts) = dp
            return data > threshold

        return kern

    return get_kernel(ctx, key, build)(cols, ())


def good_comment_declared(ctx, cols):
    """A read threaded by a mechanism the analyzer cannot see, declared
    at the site — the audited ``# lint: key=`` escape, never a blanket
    ignore."""
    # lint: key=CYLON_TPU_EMIT_IMPL -- fixture: stands in for a mechanism
    # like get_kernel's wrapping-flag key components
    impl = os.environ.get("CYLON_TPU_EMIT_IMPL", "gather")
    key = ("fixture_good_comment", len(cols))

    def build():
        def kern(dp, rep):
            if impl == "gather":
                return dp
            return rep

        return kern

    return get_kernel(ctx, key, build)(cols, ())
