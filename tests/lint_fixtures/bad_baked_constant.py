"""Seeded known-bad fixture (graft-lint rule ``baked-constant``): a
caller-supplied Python scalar is closure-captured into the jit body as a
baked XLA constant — every new value silently recompiles (or worse, the
cached program keeps the first value) because nothing threads it into
the cache key and it never rides as an operand.
"""
from cylon_tpu.engine import get_kernel


def bad_baked_constant(ctx, cols, threshold):
    key = ("fixture_bad_baked", len(cols))

    def build():
        def kern(dp, rep):
            (data, counts) = dp
            return data > threshold

        return kern

    return get_kernel(ctx, key, build)(cols, ())
