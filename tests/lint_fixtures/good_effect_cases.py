"""Known-good twins for the L3 effect pass (graft-lint ISSUE 7): the
same shapes as the bad fixtures with the invariant HELD. The pass must
report zero findings here — over-flagging these would train people to
reach for exemptions.
"""
import threading

_GOOD_CACHE = {}
_cache_lock = threading.Lock()


def remember_locked(key, value):
    """The lock-dominated twin of bad_shared_write.remember: the write
    is inside a ``with <lock>`` — guarded, not a finding."""
    with _cache_lock:
        _GOOD_CACHE[key] = value
    return value


def remember_published(key, value):
    """The GIL-atomic create-or-get publish: ``dict.setdefault`` is the
    sanctioned pattern for shared maps (engine.get_kernel), never a
    finding."""
    return _GOOD_CACHE.setdefault(key, value)


def remember_declared(key, value):
    # lint: guarded=gil -- single-word swap of an immutable value; the
    # audited GIL-atomic publish (no torn read is observable)
    _GOOD_CACHE[key] = value
    return value


def stage_host(rows):
    """The ``# lint: sync=host`` reclassification twin: ``.item()`` on a
    HOST value (a numpy scalar) is not a device sync."""
    # lint: sync=host -- rows is a host numpy array; .item() is a plain
    # python conversion, no device transfer involved
    return [r.item() for r in rows]


def dispatch_chain(table, mask):
    """A genuinely dispatch-safe public entry: device-side delegation
    only, no fetch, no shared write, no count read."""
    return _narrow(table, mask)


def _narrow(table, mask):
    return table.filter(mask)
