"""Tests for the fused distributed pipeline (parallel/pipeline.py).

This is the code path the driver's multichip dryrun and the benchmarks run:
make_distributed_join_step / make_join_groupby_step — the whole
partition -> all_to_all -> join -> aggregate chain as ONE jitted shard_map
program (reference analog: the op-DAG DisJoinOP graph,
cpp/src/cylon/ops/dis_join_op.cpp:26-71). Verified against pandas on the
global (all-shard) data, at mesh sizes {1,2,4,8}, including the overflow
flags for undersized capacities.
"""
import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from cylon_tpu.ops import join as _j
from cylon_tpu.parallel.pipeline import (
    make_distributed_join_step,
    make_join_groupby_step,
)


def _mk_mesh(devices, n):
    return Mesh(np.array(devices[:n]), ("dp",))


def _put(mesh, arr):
    return jax.device_put(jnp.asarray(arr), NamedSharding(mesh, PartitionSpec("dp")))


def _mk_table(mesh, rng, world, shard_cap, n_per_shard, keyspace, with_nulls=False):
    """Build (cols, counts_dev) plus the equivalent global pandas frame."""
    key = rng.integers(0, keyspace, world * shard_cap).astype(np.int32)
    val = rng.normal(size=world * shard_cap).astype(np.float32)
    valid = None
    if with_nulls:
        valid = rng.random(world * shard_cap) > 0.25
    counts = np.asarray(n_per_shard, np.int32)
    assert counts.shape == (world,)
    live_k, live_v, live_m = [], [], []
    for i in range(world):
        lo = i * shard_cap
        c = int(counts[i])
        live_k.append(key[lo : lo + c])
        live_v.append(val[lo : lo + c])
        if with_nulls:
            live_m.append(valid[lo : lo + c])
    gk = np.concatenate(live_k)
    gv = np.concatenate(live_v).astype(np.float64)
    if with_nulls:
        gm = np.concatenate(live_m)
        gv = np.where(gm, gv, np.nan)
    df = pd.DataFrame({"k": gk, "v": gv})
    cols = [
        (_put(mesh, key), None),
        (_put(mesh, val), _put(mesh, valid) if with_nulls else None),
    ]
    counts_dev = _put(mesh, counts)
    return cols, counts_dev, df


def _collect_rows(out_cols, out_counts, world, cap):
    """Live rows per shard chunk -> dict of column-name -> global ndarray."""
    res = []
    for data, valid in out_cols:
        d = np.asarray(data).reshape(world, cap)
        v = None if valid is None else np.asarray(valid).reshape(world, cap)
        parts = []
        cnt = np.asarray(out_counts).reshape(-1)
        for i in range(world):
            c = int(cnt[i])
            x = d[i, :c].astype(np.float64)
            if v is not None:
                x = np.where(v[i, :c], x, np.nan)
            parts.append(x)
        res.append(np.concatenate(parts))
    return res


def _multiset_equal(cols_a, cols_b):
    """Order-independent row-multiset comparison of column lists (NaN==NaN)."""
    a = np.stack([np.nan_to_num(c, nan=1.5e300) for c in cols_a], 1)
    b = np.stack([np.nan_to_num(c, nan=1.5e300) for c in cols_b], 1)
    if a.shape != b.shape:
        return False
    order_a = np.lexsort(a.T)
    order_b = np.lexsort(b.T)
    return np.allclose(a[order_a], b[order_b], rtol=1e-5, atol=1e-6)


HOWS = [("inner", _j.INNER), ("left", _j.LEFT), ("right", _j.RIGHT), ("outer", _j.FULL_OUTER)]


@pytest.mark.parametrize("world", [1, 2, 4, 8])
@pytest.mark.parametrize("how_name,how", HOWS)
def test_distributed_join_step_vs_pandas(devices, rng, world, how_name, how):
    mesh = _mk_mesh(devices, world)
    shard_cap = 32
    n_l = rng.integers(10, shard_cap, world).astype(np.int32)
    n_r = rng.integers(10, shard_cap, world).astype(np.int32)
    l_cols, l_counts, l_df = _mk_table(mesh, rng, world, shard_cap, n_l, keyspace=12)
    r_cols, r_counts, r_df = _mk_table(mesh, rng, world, shard_cap, n_r, keyspace=12)

    step = make_distributed_join_step(
        mesh, "dp", l_key_idx=(0,), r_key_idx=(0,), how=how,
        bucket_cap=world * shard_cap, join_cap=4096,
    )
    out_cols, out_counts, overflow = step((l_cols, l_counts, r_cols, r_counts), ())
    jax.block_until_ready(out_counts)
    assert int(np.asarray(overflow).sum()) == 0

    got_lk, got_lv, got_rk, got_rv = _collect_rows(out_cols, out_counts, world, 4096)
    # outer-join null sides: gather_column gives valid=False -> NaN via _collect
    exp = l_df.merge(r_df, on="k", how=how_name, suffixes=("_l", "_r"),
                     indicator=True)
    exp_lk = np.where(exp["_merge"] == "right_only", np.nan, exp["k"])
    exp_rk = np.where(exp["_merge"] == "left_only", np.nan, exp["k"])
    exp_lv = exp["v_l"].to_numpy(np.float64)
    exp_rv = exp["v_r"].to_numpy(np.float64)

    assert int(np.asarray(out_counts).sum()) == len(exp)
    assert _multiset_equal(
        [got_lk, got_lv, got_rk, got_rv],
        [np.asarray(exp_lk, np.float64), exp_lv, np.asarray(exp_rk, np.float64), exp_rv],
    )


@pytest.mark.parametrize("world", [2, 8])
def test_join_step_nullable_value_columns(devices, rng, world):
    """Null masks must survive the all_to_all exchange (shuffle_shard's
    valid-column branch) and the join gather."""
    mesh = _mk_mesh(devices, world)
    shard_cap = 32
    n = np.full((world,), 28, np.int32)
    l_cols, l_counts, l_df = _mk_table(mesh, rng, world, shard_cap, n,
                                       keyspace=10, with_nulls=True)
    r_cols, r_counts, r_df = _mk_table(mesh, rng, world, shard_cap, n,
                                       keyspace=10, with_nulls=True)
    step = make_distributed_join_step(
        mesh, "dp", l_key_idx=(0,), r_key_idx=(0,), how=_j.INNER,
        bucket_cap=world * shard_cap, join_cap=8192,
    )
    out_cols, out_counts, overflow = step((l_cols, l_counts, r_cols, r_counts), ())
    assert int(np.asarray(overflow).sum()) == 0
    got_lk, got_lv, got_rk, got_rv = _collect_rows(out_cols, out_counts, world, 8192)
    exp = l_df.merge(r_df, on="k", how="inner", suffixes=("_l", "_r"))
    assert int(np.asarray(out_counts).sum()) == len(exp)
    assert _multiset_equal(
        [got_lk, got_lv, got_rv],
        [exp["k"].to_numpy(np.float64), exp["v_l"].to_numpy(np.float64),
         exp["v_r"].to_numpy(np.float64)],
    )


@pytest.mark.parametrize("world", [2, 8])
def test_distributed_join_step_matches_eager_table(devices, rng, world):
    """Cross-check the fused path against the eager Table.distributed_join."""
    import cylon_tpu as ct

    ctx = ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:world]))
    n = 200
    lk = rng.integers(0, 40, n).astype(np.int32)
    lv = rng.normal(size=n).astype(np.float32)
    rk = rng.integers(0, 40, n).astype(np.int32)
    rv = rng.normal(size=n).astype(np.float32)
    lt = ct.Table.from_pydict(ctx, {"k": lk, "v": lv})
    rt = ct.Table.from_pydict(ctx, {"k": rk, "w": rv})
    eager = lt.distributed_join(rt, on="k", how="inner").to_pandas()

    mesh = ctx.mesh
    cap = lt.shard_cap
    l_cols = [(c.data, c.valid) for c in lt._columns.values()]
    r_cols = [(c.data, c.valid) for c in rt._columns.values()]
    step = make_distributed_join_step(
        mesh, ctx.axis_name, l_key_idx=(0,), r_key_idx=(0,), how=_j.INNER,
        bucket_cap=world * cap, join_cap=8192,
    )
    out_cols, out_counts, overflow = step(
        (l_cols, lt.counts_dev, r_cols, rt.counts_dev), ()
    )
    assert int(np.asarray(overflow).sum()) == 0
    got_lk, got_lv, got_rk, got_rv = _collect_rows(out_cols, out_counts, world, 8192)
    assert _multiset_equal(
        [got_lk, got_lv, got_rk, got_rv],
        [eager["k_x"].to_numpy(np.float64), eager["v"].to_numpy(np.float64),
         eager["k_y"].to_numpy(np.float64), eager["w"].to_numpy(np.float64)],
    )


@pytest.mark.parametrize("world", [2, 4, 8])
def test_join_groupby_step_total(devices, rng, world):
    mesh = _mk_mesh(devices, world)
    shard_cap = 32
    n_l = np.full((world,), 24, np.int32)
    n_r = np.full((world,), 20, np.int32)
    l_cols, l_counts, l_df = _mk_table(mesh, rng, world, shard_cap, n_l, keyspace=16)
    r_cols, r_counts, r_df = _mk_table(mesh, rng, world, shard_cap, n_r, keyspace=16)

    step = make_join_groupby_step(
        mesh, "dp", l_key_idx=(0,), r_key_idx=(0,), agg_col_idx=1, how=_j.INNER,
        bucket_cap=world * shard_cap, join_cap=world * shard_cap * 8, group_cap=64,
    )
    sums, ng, n_join, total = step((l_cols, l_counts, r_cols, r_counts), ())
    t = np.asarray(total)
    assert np.allclose(t, t[0], rtol=1e-5)

    exp = l_df.merge(r_df, on="k", how="inner", suffixes=("_l", "_r"))
    assert int(np.asarray(n_join).sum()) == len(exp)
    assert np.isclose(t[0], exp["v_l"].sum(), rtol=1e-4)


@pytest.mark.parametrize("world", [1, 2, 8])
def test_join_groupby_pushdown_group_sums(devices, rng, world):
    """The join+groupby-SUM pushdown (ops/join.join_sum_by_key_pushdown,
    used by make_join_groupby_step when group key == join key and the agg
    column is floating): per-group sums must match pandas as a multiset,
    not just in total."""
    mesh = _mk_mesh(devices, world)
    shard_cap = 32
    n_l = np.full((world,), 28, np.int32)
    n_r = np.full((world,), 22, np.int32)
    l_cols, l_counts, l_df = _mk_table(mesh, rng, world, shard_cap, n_l, keyspace=9)
    r_cols, r_counts, r_df = _mk_table(mesh, rng, world, shard_cap, n_r, keyspace=9)

    step = make_join_groupby_step(
        mesh, "dp", l_key_idx=(0,), r_key_idx=(0,), agg_col_idx=1, how=_j.INNER,
        bucket_cap=world * shard_cap, join_cap=world * shard_cap * 16, group_cap=64,
    )
    sums, ng, n_join, total = step((l_cols, l_counts, r_cols, r_counts), ())
    exp = (
        l_df.merge(r_df, on="k", how="inner", suffixes=("_l", "_r"))
        .groupby("k")["v_l"].sum()
    )
    got = []
    sums = np.asarray(sums).reshape(world, -1)
    for s_i, ng_i in zip(sums, np.asarray(ng).reshape(-1)):
        got += s_i[: int(ng_i)].tolist()
    assert int(np.asarray(n_join).sum()) == len(
        l_df.merge(r_df, on="k", how="inner")
    )
    assert len(got) == len(exp)
    assert np.allclose(sorted(got), sorted(exp.values), rtol=1e-4)


@pytest.mark.parametrize("world", [1, 4])
def test_join_groupby_pushdown_null_values(devices, rng, world):
    """Null aggregate values contribute 0 (SUM skip-null), matching pandas
    groupby sum over the join result."""
    mesh = _mk_mesh(devices, world)
    shard_cap = 32
    n_l = np.full((world,), 26, np.int32)
    n_r = np.full((world,), 20, np.int32)
    l_cols, l_counts, l_df = _mk_table(
        mesh, rng, world, shard_cap, n_l, keyspace=8, with_nulls=True
    )
    r_cols, r_counts, r_df = _mk_table(mesh, rng, world, shard_cap, n_r, keyspace=8)

    step = make_join_groupby_step(
        mesh, "dp", l_key_idx=(0,), r_key_idx=(0,), agg_col_idx=1, how=_j.INNER,
        bucket_cap=world * shard_cap, join_cap=world * shard_cap * 16, group_cap=64,
    )
    sums, ng, n_join, total = step((l_cols, l_counts, r_cols, r_counts), ())
    exp = l_df.merge(r_df, on="k", how="inner", suffixes=("_l", "_r"))
    assert int(np.asarray(n_join).sum()) == len(exp)
    t = np.asarray(total)
    assert np.isclose(t[0], exp["v_l"].sum(), rtol=1e-4)


def test_join_groupby_step_int_agg_generic_path(devices, rng):
    """An integer aggregate column must route through the generic
    join-then-groupby path (the pushdown accumulates in float)."""
    world = 2
    mesh = _mk_mesh(devices, world)
    shard_cap = 32
    n_l = np.full((world,), 20, np.int32)
    n_r = np.full((world,), 20, np.int32)
    l_cols, l_counts, l_df = _mk_table(mesh, rng, world, shard_cap, n_l, keyspace=7)
    r_cols, r_counts, r_df = _mk_table(mesh, rng, world, shard_cap, n_r, keyspace=7)
    # replace the value column with ints
    import jax

    iv = []
    for (d, v) in l_cols:
        iv.append((d, v))
    int_vals = np.arange(world * shard_cap, dtype=np.int32)
    iv[1] = (jax.device_put(jnp.asarray(int_vals), l_cols[0][0].sharding), None)
    l_df = l_df.copy()
    per = [int_vals.reshape(world, shard_cap)[i, :20] for i in range(world)]
    l_df["v"] = np.concatenate(per)

    step = make_join_groupby_step(
        mesh, "dp", l_key_idx=(0,), r_key_idx=(0,), agg_col_idx=1, how=_j.INNER,
        bucket_cap=world * shard_cap, join_cap=world * shard_cap * 16, group_cap=64,
    )
    sums, ng, n_join, total = step((iv, l_counts, r_cols, r_counts), ())
    exp = l_df.merge(r_df, on="k", how="inner", suffixes=("_l", "_r"))
    assert int(np.asarray(n_join).sum()) == len(exp)
    t = np.asarray(total)
    assert np.isclose(t[0], exp["v_l"].sum(), rtol=1e-5)


def test_join_step_overflow_flags(devices, rng):
    """Undersized bucket_cap / join_cap must raise the overflow flag, not
    silently truncate counts."""
    world = 4
    mesh = _mk_mesh(devices, world)
    shard_cap = 32
    n = np.full((world,), 32, np.int32)
    # all rows share one key -> every shard sends everything to one bucket
    key = np.zeros(world * shard_cap, np.int32)
    val = rng.normal(size=world * shard_cap).astype(np.float32)
    cols = [(_put(mesh, key), None), (_put(mesh, val), None)]
    counts = _put(mesh, n)

    step = make_distributed_join_step(
        mesh, "dp", l_key_idx=(0,), r_key_idx=(0,), how=_j.INNER,
        bucket_cap=8, join_cap=64,  # way too small for 128 rows on one target
    )
    out_cols, out_counts, overflow = step((cols, counts, cols, counts), ())
    assert int(np.asarray(overflow).sum()) > 0

    # properly sized: no overflow, exact count (128*128 inner matches won't
    # fit small join_cap; use adequate caps)
    step2 = make_distributed_join_step(
        mesh, "dp", l_key_idx=(0,), r_key_idx=(0,), how=_j.INNER,
        bucket_cap=world * shard_cap, join_cap=16384,
    )
    _, out_counts2, overflow2 = step2((cols, counts, cols, counts), ())
    assert int(np.asarray(overflow2).sum()) == 0
    assert int(np.asarray(out_counts2).sum()) == (world * shard_cap) ** 2


def test_graft_entry_dryrun_smoke():
    """The driver contract: dryrun_multichip(8) completes in-process."""
    import __graft_entry__ as g

    g.dryrun_multichip(8)


# ---------------------------------------------------------------------------
# product surface: Table.distributed_join(mode='fused') / DataFrame mode=
# (the execution-mode flag promoting the fused pipeline to product)
# ---------------------------------------------------------------------------
import cylon_tpu as ct


def _msort(df):
    return df.sort_values(list(df.columns)).reset_index(drop=True)


@pytest.mark.parametrize("how", ["inner", "left"])
def test_fused_join_matches_eager(world_ctx, rng, how):
    n = 600
    a = pd.DataFrame({"k": rng.integers(0, 50, n).astype(np.int64),
                      "x": rng.normal(size=n)})
    b = pd.DataFrame({"k": rng.integers(0, 50, n // 2).astype(np.int64),
                      "y": rng.normal(size=n // 2)})
    ta, tb = ct.Table.from_pandas(world_ctx, a), ct.Table.from_pandas(world_ctx, b)
    fused = ta.distributed_join(tb, on="k", how=how, mode="fused").to_pandas()
    eager = ta.distributed_join(tb, on="k", how=how, mode="eager").to_pandas()
    assert len(fused) == len(eager) == len(a.merge(b, on="k", how=how))
    pd.testing.assert_frame_equal(_msort(fused), _msort(eager), check_dtype=False)


def test_fused_join_skew_retries(ctx8, rng):
    """One hot key: the first capacity guess overflows, the retry path must
    converge to the exact result (no wrong answers under skew)."""
    n = 512
    k = np.zeros(n, np.int64)  # every row the same key on the left
    a = pd.DataFrame({"k": k, "x": rng.normal(size=n)})
    b = pd.DataFrame({"k": rng.integers(0, 4, 64).astype(np.int64),
                      "y": rng.normal(size=64)})
    ta, tb = ct.Table.from_pandas(ctx8, a), ct.Table.from_pandas(ctx8, b)
    fused = ta.distributed_join(tb, on="k", how="inner", mode="fused").to_pandas()
    exp = a.merge(b, on="k")
    assert len(fused) == len(exp)
    assert np.isclose(fused["x"].sum(), exp["x"].sum())


@pytest.mark.parametrize("num_slices", [2, 4])
@pytest.mark.parametrize("how", ["inner", "left", "outer"])
def test_fused_join_sliced_matches_eager(ctx8, rng, how, num_slices):
    """K hash-slice rounds (PARITY.md north-star lever 1) must be exactly
    the 1-slice result — slicing changes sort depth, never semantics."""
    n = 700
    a = pd.DataFrame({"k": rng.integers(0, 60, n).astype(np.int64),
                      "x": rng.normal(size=n)})
    b = pd.DataFrame({"k": rng.integers(0, 60, n // 2).astype(np.int64),
                      "y": rng.normal(size=n // 2)})
    ta, tb = ct.Table.from_pandas(ctx8, a), ct.Table.from_pandas(ctx8, b)
    sliced = ta.distributed_join(
        tb, on="k", how=how, mode="fused", num_slices=num_slices
    ).to_pandas()
    eager = ta.distributed_join(tb, on="k", how=how).to_pandas()
    assert len(sliced) == len(eager) == len(a.merge(b, on="k", how=how))
    pd.testing.assert_frame_equal(_msort(sliced), _msort(eager), check_dtype=False)


def test_fused_join_sliced_skew_retries(ctx8, rng):
    """Hot key + slices: the retry machinery must converge with slices on
    (the hot key lands in ONE slice, concentrating its round)."""
    n = 512
    a = pd.DataFrame({"k": np.zeros(n, np.int64), "x": rng.normal(size=n)})
    b = pd.DataFrame({"k": rng.integers(0, 4, 64).astype(np.int64),
                      "y": rng.normal(size=64)})
    ta, tb = ct.Table.from_pandas(ctx8, a), ct.Table.from_pandas(ctx8, b)
    fused = ta.distributed_join(
        tb, on="k", how="inner", mode="fused", num_slices=4, max_retries=6
    ).to_pandas()
    exp = a.merge(b, on="k")
    assert len(fused) == len(exp)
    assert np.isclose(fused["x"].sum(), exp["x"].sum())


def test_fused_join_string_keys(world_ctx, rng):
    a = pd.DataFrame({"s": rng.choice(["aa", "bb", "cc", "dd"], 200),
                      "x": rng.normal(size=200)})
    b = pd.DataFrame({"s": rng.choice(["bb", "cc", "ee"], 100),
                      "y": rng.normal(size=100)})
    ta, tb = ct.Table.from_pandas(world_ctx, a), ct.Table.from_pandas(world_ctx, b)
    fused = ta.distributed_join(tb, on="s", how="inner", mode="fused").to_pandas()
    exp = a.merge(b, on="s")
    assert len(fused) == len(exp)
    assert sorted(fused["s_x"].tolist()) == sorted(exp["s"].tolist())


def test_fused_mode_via_dataframe(ctx8, rng):
    env = ct.CylonEnv(config=ct.TPUConfig(devices=list(ctx8.mesh.devices.flat)))
    a = pd.DataFrame({"k": rng.integers(0, 20, 300).astype(np.int64),
                      "x": rng.normal(size=300)})
    b = pd.DataFrame({"k": rng.integers(0, 20, 200).astype(np.int64),
                      "y": rng.normal(size=200)})
    da, db = ct.DataFrame(a), ct.DataFrame(b)
    out = da.merge(db, on="k", env=env, mode="fused").to_pandas()
    exp = a.merge(b, on="k")
    assert len(out) == len(exp)
