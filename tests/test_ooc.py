"""Out-of-core join (VERDICT round-2 item 7): both inputs exceed any single
device allocation we permit; the Grace-style partitioned dag join streams
chunks through bounded device memory and matches pandas.

Reference analog: the byte-chunked streaming shuffle
(arrow/arrow_all_to_all.cpp:83-141) + DisJoinOP (ops/dis_join_op.cpp:26-71).
"""
import numpy as np
import pandas as pd
import pytest

import cylon_tpu as ct
from cylon_tpu.parallel.ooc import OutOfCoreJoin


def _chunks(df, chunk_rows):
    for i in range(0, len(df), chunk_rows):
        part = df.iloc[i : i + chunk_rows]
        yield {c: part[c].to_numpy() for c in df.columns}


def test_ooc_join_exceeds_device_budget(ctx8):
    rng = np.random.default_rng(3)
    n = 60_000  # per side
    chunk_rows = 4_000
    ldf = pd.DataFrame(
        {
            "k": rng.integers(0, 20_000, n).astype(np.int32),
            "v": rng.normal(size=n).astype(np.float32),
        }
    )
    rdf = pd.DataFrame(
        {
            "k": rng.integers(0, 20_000, n).astype(np.int32),
            "w": rng.normal(size=n).astype(np.float32),
        }
    )

    job = OutOfCoreJoin(ctx8, on="k", how="inner", num_buckets=16)
    sink = job.execute(_chunks(ldf, chunk_rows), _chunks(rdf, chunk_rows))

    expect = ldf.merge(rdf, on="k", how="inner")
    assert sink.rows == len(expect)

    got = pd.DataFrame(sink.result_pydict())
    got = (
        got[["k_x", "v", "w"]]
        .rename(columns={"k_x": "k"})
        .sort_values(["k", "v", "w"], kind="mergesort")
        .reset_index(drop=True)
    )
    want = (
        expect.sort_values(["k", "v", "w"], kind="mergesort")
        .reset_index(drop=True)[["k", "v", "w"]]
    )
    pd.testing.assert_frame_equal(got, want, check_dtype=False, atol=1e-6)

    # the out-of-core guarantee, compared like-for-like: max_device_cap is
    # the peak CONCURRENT resident device rows (two staged bucket pairs +
    # one result table, per the double-buffered bound in ooc.py); the
    # in-memory join's concurrent residency under the same accounting is
    # both input shards + the output shard (~3n/world rows, before
    # cap rounding). Every ooc stage must stay at bucket scale, well
    # below that.
    full_resident = 3 * n // ctx8.world_size
    assert job.max_device_cap < full_resident // 2, (
        job.max_device_cap, full_resident,
    )


def test_ooc_device_cap_scales_with_buckets(ctx8):
    """Pin the ~total/K residency bound directly (advisor round-3): doubling
    num_buckets must shrink peak resident device rows, which a regression to
    full-table residency on any stage could not satisfy."""
    rng = np.random.default_rng(7)
    n = 48_000
    # near-unique keys: the bucket-join OUTPUT stays input-scale, so the
    # ~total/K INPUT residency num_buckets controls is what the max sees
    # (with ~5 matches/key the output tables dominate the join-phase peak
    # and round to the same pow2 cap at adjacent bucket counts)
    ldf = pd.DataFrame({"k": rng.integers(0, 4 * n, n).astype(np.int32),
                        "v": rng.normal(size=n).astype(np.float32)})
    rdf = pd.DataFrame({"k": rng.integers(0, 4 * n, n).astype(np.int32),
                        "w": rng.normal(size=n).astype(np.float32)})
    caps = {}
    for k in (8, 16):
        job = OutOfCoreJoin(ctx8, on="k", how="inner", num_buckets=k)
        sink = job.execute(_chunks(ldf, 4_000), _chunks(rdf, 4_000))
        assert sink.rows == len(ldf.merge(rdf, on="k"))
        # the JOIN phase is what num_buckets bounds (~total/K); the spill
        # phase's chunk-sized residency is bucket-count-independent and
        # can dominate the global max at test sizes
        caps[k] = job.join_phase_device_cap
    # power-of-2 cap rounding quantizes the residency, so require a real
    # drop (not just <=): halving bucket size must at least halve one
    # rounding step, i.e. strictly fewer peak rows
    assert caps[16] < caps[8], caps


def test_ooc_join_fused_override(ctx8):
    """mode='fused' bucket joins (1 sync/bucket) stay correct — the
    residency bound is deliberately NOT asserted here (the fused join's
    speculative capacity trades the ~total/K guarantee for fewer syncs)."""
    rng = np.random.default_rng(5)
    n = 20_000
    ldf = pd.DataFrame({"k": rng.integers(0, 4_000, n).astype(np.int32),
                        "v": rng.normal(size=n).astype(np.float32)})
    rdf = pd.DataFrame({"k": rng.integers(0, 4_000, n).astype(np.int32),
                        "w": rng.normal(size=n).astype(np.float32)})
    job = OutOfCoreJoin(ctx8, on="k", how="inner", num_buckets=8, mode="fused")
    sink = job.execute(_chunks(ldf, 4_000), _chunks(rdf, 4_000))
    assert sink.rows == len(ldf.merge(rdf, on="k"))


def test_ooc_join_empty_bucket_sides(ctx8):
    """Keys chosen so some buckets are one-sided or empty: inner join must
    skip them without error."""
    ldf = pd.DataFrame({"k": np.array([1, 1, 2], np.int32), "v": np.arange(3.0)})
    rdf = pd.DataFrame({"k": np.array([2, 3], np.int32), "w": np.arange(2.0)})
    job = OutOfCoreJoin(ctx8, on="k", how="inner", num_buckets=8)
    sink = job.execute(_chunks(ldf, 2), _chunks(rdf, 1))
    expect = ldf.merge(rdf, on="k")
    assert sink.rows == len(expect) == 1
