"""Unit tests for the chained-lexsort and run-scan primitives in ops.sort.

These back every relational kernel (join probe, set algebra, factorize,
groupby ordering) since the round-2 sorted-space redesign, so they get
direct property tests against numpy oracles — not just indirect coverage
through the table ops."""
import numpy as np
import jax.numpy as jnp
import pytest

from cylon_tpu.ops.sort import (
    lexsort_indices,
    lexsort_with_payload,
    run_count_from,
    run_count_upto,
    run_start_broadcast,
    sentinel_compact,
)


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("k", [1, 2, 3, 4])
def test_lexsort_indices_matches_numpy(seed, k):
    rng = np.random.default_rng(seed)
    n = 257
    lanes = [rng.integers(0, 7, n).astype(np.int32) for _ in range(k)]
    got = np.asarray(lexsort_indices([jnp.asarray(l) for l in lanes], n))
    want = np.lexsort(tuple(lanes))
    np.testing.assert_array_equal(got, want)


def test_lexsort_with_payload_keep_lanes_consistency():
    rng = np.random.default_rng(3)
    n = 128
    lanes = [jnp.asarray(rng.integers(0, 5, n).astype(np.uint32)) for _ in range(3)]
    pay = jnp.arange(n, dtype=jnp.int32)
    kept_lanes, pays_keep = lexsort_with_payload(lanes, [pay], keep_lanes=True)
    none_lanes, pays_drop = lexsort_with_payload(lanes, [pay], keep_lanes=False)
    assert none_lanes is None
    np.testing.assert_array_equal(np.asarray(pays_keep[0]), np.asarray(pays_drop[0]))
    # kept sorted lanes are the input lanes gathered by the order
    order = np.asarray(pays_keep[0])
    for lane, slane in zip(lanes, kept_lanes):
        np.testing.assert_array_equal(np.asarray(slane), np.asarray(lane)[order])


def _runs_from_sorted(skey):
    new_run = np.ones(len(skey), bool)
    new_run[1:] = skey[1:] != skey[:-1]
    return new_run


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_run_scans_against_bruteforce(seed):
    rng = np.random.default_rng(seed)
    n = 211
    skey = np.sort(rng.integers(0, 12, n)).astype(np.int32)
    flag = rng.random(n) < 0.4
    new_run = _runs_from_sorted(skey)
    upto = np.asarray(run_count_upto(jnp.asarray(new_run), jnp.asarray(flag)))
    frm = np.asarray(run_count_from(jnp.asarray(new_run), jnp.asarray(flag)))
    for i in range(n):
        run = skey == skey[i]
        idx = np.nonzero(run)[0]
        assert upto[i] == int(flag[idx[idx <= i]].sum()), i
        assert frm[i] == int(flag[idx[idx >= i]].sum()), i


def test_run_start_broadcast_requires_nondecreasing_prefix():
    skey = np.asarray([0, 0, 1, 1, 1, 3], np.int32)
    new_run = _runs_from_sorted(skey)
    prefix = np.asarray([0, 1, 1, 2, 2, 4], np.int32)  # non-decreasing
    got = np.asarray(run_start_broadcast(jnp.asarray(new_run), jnp.asarray(prefix)))
    want = np.asarray([0, 0, 1, 1, 1, 4], np.int32)  # each run's first value
    np.testing.assert_array_equal(got, want)


def test_sentinel_compact_orders_kept_rows():
    rng = np.random.default_rng(5)
    n = 97
    keep = rng.random(n) < 0.3
    pay = np.arange(n, dtype=np.int32)
    big = np.int32(2**31 - 1)
    key = np.where(keep, pay, big).astype(np.int32)
    (idx,) = sentinel_compact(jnp.asarray(key), [jnp.asarray(pay)])
    k = int(keep.sum())
    np.testing.assert_array_equal(np.asarray(idx)[:k], pay[keep])
