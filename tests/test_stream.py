"""Streaming ingestion + incremental view maintenance (ISSUE 16).

The invariant under test everywhere: an incremental refresh is
indistinguishable from the ``CYLON_TPU_NO_IVM=1`` full-recompute oracle
(exact canonicalized equality — test data uses integer-valued floats so
float32 sums associate exactly), generations never alias in any
fingerprint-keyed cache, and every failure ends typed with the prior
generation still queryable and the state arena rolled back.
"""
import os

import numpy as np
import pytest

import cylon_tpu as ct
from cylon_tpu import fault, stream
from cylon_tpu.fault import StreamIngestError
from cylon_tpu.fault import inject as finject
from cylon_tpu.fault.errors import CylonError
from cylon_tpu.plan import lazy as lazy_mod


@pytest.fixture(scope="module", params=[1, 4, 8])
def sctx(request, devices):
    """Worlds {1, 4, 8}: the ISSUE-mandated differential sweep."""
    n = request.param
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:n]))


@pytest.fixture(scope="module")
def ctx4(devices):
    return ct.CylonContext.init_distributed(ct.TPUConfig(devices=devices[:4]))


@pytest.fixture(autouse=True)
def _disarm(monkeypatch):
    monkeypatch.delenv("CYLON_TPU_FAULTS", raising=False)
    monkeypatch.delenv("CYLON_TPU_NO_IVM", raising=False)
    fault.reset()
    yield
    fault.reset()


def _str_keys(rng, n, keyspace=16, null_p=0.1):
    k = rng.choice([f"s{i:02d}" for i in range(keyspace)], n).astype(object)
    if null_p:
        k[rng.random(n) < null_p] = None
    return k


def _batch(rng, n, null_p=0.1):
    """Dict batch: string keys (with nulls), integer-valued float32
    payload — float sums associate exactly, so oracle equality is ==."""
    return {
        "k": _str_keys(rng, n, null_p=null_p),
        "v": rng.integers(-40, 40, n).astype(np.float32),
    }


def _canon(t):
    df = t.to_pandas()
    for c in df.columns:
        if df[c].dtype == object:
            df[c] = df[c].fillna("\x00<null>")
    return df.sort_values(list(df.columns)).reset_index(drop=True)


def _assert_equal(got, want):
    a, b = _canon(got), _canon(want)
    assert list(a.columns) == list(b.columns)
    # The incremental path must reproduce the full-recompute SCHEMA too,
    # not just the values (host-merged partials rebuild via object arrays).
    assert list(a.dtypes) == list(b.dtypes), f"{list(a.dtypes)} != {list(b.dtypes)}"
    assert len(a) == len(b), f"{len(a)} rows != oracle {len(b)}"
    for c in a.columns:
        av, bv = a[c].to_numpy(), b[c].to_numpy()
        if a[c].dtype == object:
            assert (av == bv).all(), f"column {c} mismatch"
        else:
            np.testing.assert_array_equal(av, bv, err_msg=f"column {c}")


def _oracle(build, *sources):
    with stream.ivm_disabled():
        return stream.view(build, *sources).refresh()


# ---------------------------------------------------------------------------
# differentials vs the CYLON_TPU_NO_IVM=1 oracle, worlds {1, 4, 8}
# ---------------------------------------------------------------------------

def test_groupby_differential(sctx, rng):
    """Streaming scan -> filter -> groupby, multi-append, nulls."""
    tab = stream.AppendableTable(sctx, _batch(rng, 400))
    build = lambda t: (
        t.lazy().filter(ct.col("v") > -10).groupby("k", {"v": ["sum", "min"]})
    )
    v = stream.view(build, tab)
    _assert_equal(v.refresh(), _oracle(build, tab))
    for n in (150, 1, 90):  # multi-append including a 1-row delta
        tab.append(_batch(rng, n))
        _assert_equal(v.refresh(), _oracle(build, tab))
    assert v.stats["inc"] == 3 and v.stats["full"] == 1


def test_join_differential_both_sides(sctx, rng):
    """Inner join with BOTH sides streaming, groupby root, interleaved
    appends folded into single refreshes."""
    left = stream.AppendableTable(sctx, _batch(rng, 300))
    right = stream.AppendableTable(sctx, {
        "rk": _str_keys(rng, 80),
        "w": rng.integers(0, 30, 80).astype(np.float32),
    })
    build = lambda lt, rt: (
        lt.lazy().join(rt.lazy(), left_on="k", right_on="rk")
        .groupby("k", {"v": "sum", "w": "max"})
    )
    v = stream.view(build, left, right)
    v.refresh()
    # two left appends + one right append before ONE refresh
    left.append(_batch(rng, 120))
    right.append({"rk": _str_keys(rng, 40),
                  "w": rng.integers(0, 30, 40).astype(np.float32)})
    left.append(_batch(rng, 60))
    _assert_equal(v.refresh(), _oracle(build, left, right))
    assert v.stats["inc"] == 1


def test_filter_only_differential(sctx, rng):
    """No aggregate root: the delta just rides the Filter chain and the
    result is prev ++ chain(delta) (bag concat, no dedup)."""
    tab = stream.AppendableTable(sctx, _batch(rng, 200))
    build = lambda t: t.lazy().filter(ct.col("v") >= 0)
    v = stream.view(build, tab)
    v.refresh()
    tab.append(_batch(rng, 80))
    tab.append(_batch(rng, 80))  # duplicates across appends must survive
    _assert_equal(v.refresh(), _oracle(build, tab))
    assert v.stats["inc"] >= 1


def test_mean_falls_back_full(ctx4, rng):
    """mean is not mergeable from its own output: classified fallback,
    still oracle-equal."""
    tab = stream.AppendableTable(ctx4, _batch(rng, 150))
    build = lambda t: t.lazy().groupby("k", {"v": "mean"})
    v = stream.view(build, tab)
    v.refresh()
    tab.append(_batch(rng, 60))
    _assert_equal(v.refresh(), _oracle(build, tab))
    assert v.stats["fallback"] == 1 and v.stats["inc"] == 0


def test_empty_delta_and_noop(ctx4, rng):
    tab = stream.AppendableTable(ctx4, _batch(rng, 100))
    v = stream.view(lambda t: t.lazy().groupby("k", {"v": "sum"}), tab)
    r1 = v.refresh()
    g = tab.generation
    assert tab.append({"k": np.array([], object),
                       "v": np.array([], np.float32)}) == g  # no gen bump
    assert v.refresh() is r1 and v.stats["noop"] == 1  # nothing moved


def test_append_during_inflight_refresh(ctx4, rng):
    """An append landing between plan and commit must not be silently
    folded in: the commit publishes the PLANNED generation and the view
    stays stale, so the next refresh picks the new rows up."""
    tab = stream.AppendableTable(ctx4, _batch(rng, 200))
    build = lambda t: t.lazy().groupby("k", {"v": "sum"})
    v = stream.view(build, tab)
    v.refresh()
    tab.append(_batch(rng, 50))
    mode, lf, commit = v._plan_refresh()     # refresh in flight
    assert mode == "inc"
    tab.append(_batch(rng, 70))              # lands mid-flight
    commit(lf.collect())
    assert v.generations == [1] and v.stale()
    _assert_equal(v.refresh(), _oracle(build, tab))


# ---------------------------------------------------------------------------
# generation identity: plans can never alias across refreshes
# ---------------------------------------------------------------------------

def test_generation_keyed_fingerprint_no_aliasing(ctx4, rng):
    tab = stream.AppendableTable(ctx4, _batch(rng, 100))
    build = lambda t: t.lazy().groupby("k", {"v": "sum"})
    fps = []
    for _ in range(3):
        snap = tab.table()
        fps.append(lazy_mod.gated_fingerprint(build(snap).plan))
        tab.append(_batch(rng, 30))
    assert len(set(fps)) == 3, "same plan shape aliased across generations"
    # and the delta stamp is distinct from every snapshot stamp
    d = tab.delta_table(0)
    fp_d = lazy_mod.gated_fingerprint(build(d).plan)
    assert fp_d not in fps


def test_snapshot_descriptors_invalidated(ctx4, rng):
    """Appends invalidate Ordering/ColStat: snapshots are re-encoded
    fresh and never inherit a stale descriptor from an older
    generation's snapshot."""
    tab = stream.AppendableTable(ctx4, {
        "k": np.arange(64, dtype=np.int64),
        "v": np.ones(64, np.float32),
    })
    s0 = tab.table()
    s0.sort("k")  # stamp an ordering + stats onto the gen-0 snapshot
    tab.append({"k": np.array([3, 1], np.int64),
                "v": np.array([1.0, 1.0], np.float32)})
    s1 = tab.table()
    assert s1 is not s0
    assert s1._ordering is None and len(s1._stats) == 0
    d = tab.delta_table(0)
    assert d._ordering is None and len(d._stats) == 0


# ---------------------------------------------------------------------------
# ingest contract: schema validation, rollback, budget, watermarks
# ---------------------------------------------------------------------------

def test_append_schema_rejected_and_rolled_back(ctx4, rng):
    tab = stream.AppendableTable(ctx4, _batch(rng, 50))
    g, rows = tab.generation, tab.row_count
    snap_before = tab.table()
    for bad in (
        {"k": _str_keys(rng, 4), "WRONG": np.ones(4, np.float32)},
        {"k": _str_keys(rng, 4)},                                  # missing col
        {"k": np.arange(4), "v": np.ones(4, np.float32)},          # int keys
        {"k": _str_keys(rng, 4), "v": np.ones(3, np.float32)},     # ragged
        {"k": _str_keys(rng, 4), "v": np.array(["x"] * 4, object)},
    ):
        with pytest.raises(StreamIngestError) as ei:
            tab.append(bad)
        assert ei.value.retryable and ei.value.scope == "table"
    assert tab.generation == g and tab.row_count == rows
    _assert_equal(tab.table(), snap_before)  # prior gen still queryable


def test_watermarks_and_state_budget(ctx4, rng, monkeypatch):
    tab = stream.AppendableTable(ctx4, _batch(rng, 100))
    tab.append(_batch(rng, 40))
    tab.append(_batch(rng, 7))
    assert [tab.watermark(g) for g in range(3)] == [100, 140, 147]
    assert tab.rows_since(1) == 7 and tab.rows_since(0) == 47
    assert tab.delta_table(1).row_count == 7
    monkeypatch.setenv("CYLON_TPU_STREAM_STATE_BUDGET", "1")
    with pytest.raises(StreamIngestError):
        tab.append(_batch(rng, 10))
    assert tab.generation == 2 and tab.row_count == 147


def test_chunked_staging(ctx4, rng, monkeypatch):
    monkeypatch.setenv("CYLON_TPU_STREAM_CHUNK_ROWS", "16")
    tab = stream.AppendableTable(ctx4, _batch(rng, 10))
    tab.append(_batch(rng, 50))  # 4 chunks
    assert tab.row_count == 60
    _assert_equal(
        stream.view(lambda t: t.lazy().groupby("k", {"v": "sum"}), tab)
        .refresh(),
        _oracle(lambda t: t.lazy().groupby("k", {"v": "sum"}), tab),
    )


# ---------------------------------------------------------------------------
# fault seams: typed failures, state retention
# ---------------------------------------------------------------------------

def test_fault_append_rolls_back(ctx4, rng, monkeypatch):
    tab = stream.AppendableTable(ctx4, _batch(rng, 80))
    snap = tab.table()
    monkeypatch.setenv("CYLON_TPU_FAULTS", "stream.append:n=1")
    fault.reset()
    with pytest.raises(StreamIngestError):
        tab.append(_batch(rng, 20))
    assert finject.fired("stream.append") == 1
    assert tab.generation == 0 and tab.row_count == 80
    _assert_equal(tab.table(), snap)
    assert tab.append(_batch(rng, 20)) == 1  # injector exhausted: recovers


def test_fault_refresh_retains_state(ctx4, rng, monkeypatch):
    tab = stream.AppendableTable(ctx4, _batch(rng, 80))
    build = lambda t: t.lazy().groupby("k", {"v": "sum"})
    v = stream.view(build, tab)
    r0 = v.refresh()
    tab.append(_batch(rng, 30))
    monkeypatch.setenv("CYLON_TPU_FAULTS", "stream.refresh:n=1")
    fault.reset()
    with pytest.raises(CylonError):
        v.refresh()
    assert finject.fired("stream.refresh") == 1
    assert v._result is r0 and v.generations == [0]  # untouched
    _assert_equal(v.refresh(), _oracle(build, tab))  # same delta retries


def test_stream_fault_spec_validation():
    with pytest.raises(finject.FaultSpecError):
        finject.parse_spec("stream.append:kind=exec")  # errno-only seam
    finject.parse_spec("stream.append:n=1:kind=ENOSPC")
    finject.parse_spec("stream.refresh:kind=timeout")  # typed-kind seam
    with pytest.raises(finject.FaultSpecError):
        finject.parse_spec("stream.refresh:match=abc")  # unkeyed seam


# ---------------------------------------------------------------------------
# subscriptions
# ---------------------------------------------------------------------------

def test_subscription_re_resolution(ctx4, rng):
    tab = stream.AppendableTable(ctx4, _batch(rng, 200))
    build = lambda t: t.lazy().groupby("k", {"v": "sum"})
    sub = stream.subscribe(stream.view(build, tab))
    r1 = sub.result()
    assert sub.done() and not sub.stale()
    assert sub.result() is r1               # fresh: retained, no dispatch
    tab.append(_batch(rng, 60))
    assert sub.stale() and not sub.done()   # append marked it stale
    _assert_equal(sub.result(), _oracle(build, tab))
    assert sub.done()


def test_subscription_refresh_async_future(ctx4, rng):
    tab = stream.AppendableTable(ctx4, _batch(rng, 150))
    build = lambda t: t.lazy().groupby("k", {"v": "sum"})
    sub = stream.subscribe(stream.view(build, tab))
    fut = sub.refresh_async()
    got = fut.result(timeout=120)
    _assert_equal(got, _oracle(build, tab))
    tab.append(_batch(rng, 40))
    fut2 = sub.refresh_async()              # rides the serve scheduler
    _assert_equal(fut2.result(timeout=120), _oracle(build, tab))
    sub.close()


def test_subscription_failed_refresh_stays_stale(ctx4, rng, monkeypatch):
    tab = stream.AppendableTable(ctx4, _batch(rng, 100))
    sub = stream.subscribe(
        stream.view(lambda t: t.lazy().groupby("k", {"v": "sum"}), tab)
    )
    sub.result()
    tab.append(_batch(rng, 30))
    monkeypatch.setenv("CYLON_TPU_FAULTS", "stream.refresh:n=1")
    fault.reset()
    with pytest.raises(CylonError):
        sub.result()
    assert sub.stale()                      # not wedged fresh
    monkeypatch.delenv("CYLON_TPU_FAULTS")
    fault.reset()
    _assert_equal(
        sub.result(),
        _oracle(lambda t: t.lazy().groupby("k", {"v": "sum"}), tab),
    )
