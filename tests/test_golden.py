"""Golden-file distributed-op tests, verified by the library itself.

Reference analog (SURVEY.md §4): CTest runs each suite under mpirun -np
{1,2,4} with per-rank input CSVs (cpp/test/join_test.cpp:21-24) and golden
outputs; verification is SET-equality via the library — row counts match and
``Subtract(result, expected)`` is empty both ways (test_utils.hpp:37-59).
Here the same four per-rank files drive every mesh size (read_csv re-splits),
and the goldens were generated once by tests/data/gen_goldens.py (the
EXECUTE-toggle analog).
"""
import os

import pandas as pd
import pytest

import cylon_tpu as ct

DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")


def _inputs(ctx, side):
    paths = [os.path.join(DATA, f"csv{side}_{r}.csv") for r in range(4)]
    return ct.read_csv(ctx, paths)


def _golden(ctx, name):
    return ct.read_csv(ctx, os.path.join(DATA, f"{name}.csv"))


def assert_set_equal(got: ct.Table, expect: ct.Table):
    """The reference's verification scheme: counts + two-way Subtract."""
    assert got.row_count == expect.row_count, (got.row_count, expect.row_count)
    assert got.column_names == expect.column_names, (
        got.column_names, expect.column_names,
    )
    fwd = got.distributed_subtract(expect)
    assert fwd.row_count == 0, f"{fwd.row_count} rows in result but not golden"
    bwd = expect.distributed_subtract(got)
    assert bwd.row_count == 0, f"{bwd.row_count} rows in golden but not result"


def assert_multiset_equal(got: ct.Table, expect: ct.Table, columns):
    """Full multiset comparison (no dedup): sort both frames on all columns
    and compare exactly, so wrong duplicate multiplicities fail. Stronger
    than the reference's count+Subtract check (test_utils.hpp:37-59), which
    a swapped-multiplicity bug could pass."""
    gp = got.to_pandas()[columns]
    ep = expect.to_pandas()[columns]
    assert len(gp) == len(ep), (len(gp), len(ep))
    gs = gp.sort_values(columns).reset_index(drop=True)
    es = ep.sort_values(columns).reset_index(drop=True)
    pd.testing.assert_frame_equal(gs, es, check_dtype=False)


@pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
def test_golden_join(world_ctx, how):
    a = _inputs(world_ctx, 1)
    b = _inputs(world_ctx, 2)
    got = a.distributed_join(b, on="k", how=how, suffixes=("_x", "_y"))
    expect = _golden(world_ctx, f"join_{how}")
    # join emits k twice (k_x/k_y); pandas merges them — align schemas
    got = got.rename({"k_x": "k"}).drop(["k_y"]) if "k_x" in got.column_names else got
    assert got.row_count == expect.row_count
    common = [c for c in expect.column_names if c in got.column_names]
    assert_multiset_equal(got, expect, common)


def test_golden_union(world_ctx):
    got = _inputs(world_ctx, 1).distributed_union(_inputs(world_ctx, 2))
    assert_set_equal(got, _golden(world_ctx, "union"))


def test_golden_subtract(world_ctx):
    got = _inputs(world_ctx, 1).distributed_subtract(_inputs(world_ctx, 2))
    assert_set_equal(got, _golden(world_ctx, "subtract"))


def test_golden_intersect(world_ctx):
    got = _inputs(world_ctx, 1).distributed_intersect(_inputs(world_ctx, 2))
    assert_set_equal(got, _golden(world_ctx, "intersect"))


def test_golden_sort(world_ctx):
    got = _inputs(world_ctx, 1).distributed_sort(["k", "v"])
    expect = _golden(world_ctx, "sort_kv")
    # global ordering check on the gathered frame (sort is not a set op)
    gp = got.to_pandas()
    ep = expect.to_pandas().reset_index(drop=True)
    pd.testing.assert_frame_equal(
        gp[["k", "v"]].reset_index(drop=True), ep[["k", "v"]], check_dtype=False
    )


def test_golden_groupby(world_ctx):
    got = _inputs(world_ctx, 1).distributed_groupby("k", {"v": "sum"})
    assert_set_equal(got, _golden(world_ctx, "groupby_sum"))


def test_golden_unique(world_ctx):
    got = _inputs(world_ctx, 1).distributed_unique()
    assert_set_equal(got, _golden(world_ctx, "unique"))
